"""Sequential-vs-batched turn parity (the batched turn kernel's contract).

The batched round (`ops/preempt._rounds_batched`, `ops/allocate._round_batched`)
must replay the sequential turn loop's decisions BIT-FOR-BIT — identical
bind/evict streams, identical task->node pairing, identical round counts.
The soak here runs both engines action-for-action over randomized loaded
clusters at q in {8, 64, 512} and asserts every decision-bearing
AllocState field equal after every action.  The matrix covers:

* reclaim: canon-sequential vs sorted-space vs the ROUND-BATCHED canon
  engine (`_reclaim_canon_batched` — phase-A pops/eligibility/per-node
  sums with a thin clean tail and a sequential fallback after the
  round's first claim);
* allocate/backfill: batched (deferred) vs immediate rounds, with the
  feasibility-pruned candidate panels forced on (`prune=True,
  prune_floor=1`) so the compacted branches run on these small worlds;
* preempt: the batched turn kernel with the incremental round gate ON
  and OFF vs the sequential turn loop.

A directed two-queues-one-victim-queue oracle case pins the cross-queue
same-victim contention class explicitly under BOTH reclaim engines.
"""
import dataclasses
import functools

import numpy as np
import pytest

from kube_arbitrator_tpu.cache import SimCluster, build_snapshot, generate_cluster
from kube_arbitrator_tpu.framework.conf import SchedulerConfig

GB = 1024**3
FIELDS = (
    "task_status", "task_node", "evicted_for", "job_ready_cnt",
    "group_placed", "job_alloc", "queue_alloc", "node_num_tasks",
    # decision-audit attribution (utils/audit.py): decision-NEUTRAL by
    # construction, but the preemptor→victim edges must still be
    # bit-identical across engines or the audit trail would depend on
    # which engine ran — the soak pins claimant/phase/round too
    "evict_claimant", "evict_phase", "evict_round",
)


@functools.lru_cache(maxsize=None)
def _engines():
    """Module-cached jitted engines: the soak's parametrize matrix runs
    3 seeds per q with IDENTICAL shapes, so sharing one jitted callable
    per engine compiles once per q instead of once per (q, seed) — the
    matrix is compile-dominated (tiny worlds, many engines)."""
    import jax

    from kube_arbitrator_tpu.ops.cycle import commit_cycle, open_session
    from kube_arbitrator_tpu.ops.preempt import (
        _reclaim_canon,
        _reclaim_canon_batched,
        _reclaim_canon_optimistic,
        _reclaim_fast,
        preempt_action,
    )

    tiers = SchedulerConfig.default().tiers
    return tiers, {
        "open": jax.jit(lambda s: open_session(s, tiers)),
        "commit": jax.jit(commit_cycle),
        "reclaim_canon": jax.jit(
            lambda st, se, s: _reclaim_canon(st, se, s, tiers, 100_000)
        ),
        "reclaim_fast": jax.jit(
            lambda st, se, s: _reclaim_fast(st, se, s, tiers, 100_000)
        ),
        "reclaim_batched": jax.jit(
            lambda st, se, s: _reclaim_canon_batched(st, se, s, tiers, 100_000)
        ),
        "reclaim_optimistic": jax.jit(
            lambda st, se, s: _reclaim_canon_optimistic(st, se, s, tiers, 100_000)
        ),
        "preempt_gate_on": jax.jit(
            lambda st, se, s: preempt_action(
                st, se, s, tiers, turn_batch=True, round_gate=True
            )
        ),
        "preempt_gate_off": jax.jit(
            lambda st, se, s: preempt_action(
                st, se, s, tiers, turn_batch=True, round_gate=False
            )
        ),
        "preempt_seq": jax.jit(
            lambda st, se, s: preempt_action(st, se, s, tiers, turn_batch=False)
        ),
    }


def _open(st):
    tiers, eng = _engines()
    sess, state = eng["open"](st)
    return tiers, sess, state


def _assert_state_equal(a, b, ctx):
    for f in FIELDS:
        x, y = np.asarray(getattr(a, f)), np.asarray(getattr(b, f))
        assert np.array_equal(x, y), f"{ctx}: {f} diverged"
    assert int(a.rounds) == int(b.rounds), (
        f"{ctx}: round counts diverged ({int(a.rounds)} vs {int(b.rounds)})"
    )


def _world(q, seed):
    # jobs > queues so most queues hold a claimant and a fair share hold
    # two jobs (the phase-1 victim shape); oversubscribed so evictive
    # actions have work
    return generate_cluster(
        num_nodes=48,
        num_jobs=max(12, q + q // 8),
        tasks_per_job=4,
        num_queues=q,
        seed=seed,
        node_cpu_milli=4000,
        node_memory=8 * GB,
        running_fraction=0.5,
    )


@pytest.mark.parametrize("q", [8, 64, 512])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_sequential_vs_batched_decision_soak(q, seed):
    """3 seeds x {q=8, 64, 512} x {reclaim, allocate, backfill, preempt}:
    thread one state through the full action list with the BATCHED
    engines and, stage-by-stage, check the SEQUENTIAL engine from the
    same entry state produces the identical AllocState (bind/evict
    streams ride task_status/task_node/evicted_for) and round count.
    The batched result is threaded forward (the production path)."""
    from kube_arbitrator_tpu.ops.allocate import allocate_action

    sim = _world(q, seed)
    st = build_snapshot(sim.cluster).tensors
    tiers, sess, state = _open(st)
    eng = _engines()[1]

    # ---- reclaim: canon-sequential vs sorted-space vs round-batched ----
    canon = eng["reclaim_canon"](st, sess, state)
    fast = eng["reclaim_fast"](st, sess, state)
    rbatched = eng["reclaim_batched"](st, sess, state)
    roptim = eng["reclaim_optimistic"](st, sess, state)
    _assert_state_equal(canon, fast, f"reclaim q={q} seed={seed}")
    _assert_state_equal(
        canon, rbatched, f"reclaim-batched q={q} seed={seed}"
    )
    # the OPTIMISTIC engine: speculative parallel claims revalidated-or-
    # discarded at its in-window commit gate must leave decisions AND
    # round counts identical to the sequential canon walk — conflicts
    # only ever discard speculation, never change a committed claim
    _assert_state_equal(
        canon, roptim, f"reclaim-optimistic q={q} seed={seed}"
    )
    assert int(roptim.rounds_gated) <= int(roptim.rounds)
    assert int(roptim.claim_conflicts) >= 0
    assert int(canon.claim_conflicts) == 0, (
        "only the optimistic engine may count claim conflicts"
    )
    # the batched result is threaded forward (the production path)
    state = rbatched

    # ---- allocate + backfill: batched (deferred, feasibility-pruned)
    # vs immediate rounds ----
    for best_effort in (False, True):
        name = "backfill" if best_effort else "allocate"
        batched = allocate_action(
            st, sess, state, tiers, best_effort_pass=best_effort,
            turn_batch=True, prune=True, prune_floor=1,
        )
        seq = allocate_action(
            st, sess, state, tiers, best_effort_pass=best_effort, turn_batch=False
        )
        _assert_state_equal(batched, seq, f"{name} q={q} seed={seed}")
        state = batched

    # ---- preempt: batched turn kernel, round gate ON and OFF, vs the
    # sequential turn loop ----
    gate_on = eng["preempt_gate_on"](st, sess, state)
    gate_off = eng["preempt_gate_off"](st, sess, state)
    seq = eng["preempt_seq"](st, sess, state)
    _assert_state_equal(gate_on, seq, f"preempt gate-on q={q} seed={seed}")
    _assert_state_equal(gate_off, seq, f"preempt gate-off q={q} seed={seed}")
    assert int(gate_off.rounds_gated) == 0, "gate-off must never count gated"
    state = gate_on

    # the run must have exercised the evictive machinery, or the parity
    # above is vacuous (placements may land as PIPELINED claims rather
    # than committed binds when the claimant gang stays short)
    dec = eng["commit"](st, sess, state)
    from kube_arbitrator_tpu.api import TaskStatus

    ts = np.asarray(dec.task_status)
    placed = int(np.asarray(dec.bind_mask).sum()) + int(
        (ts == int(TaskStatus.PIPELINED)).sum()
    )
    assert int(np.asarray(dec.evict_mask).sum()) > 0, "vacuous soak: no evictions"
    assert placed > 0, "vacuous soak: nothing placed or pipelined"


def test_two_queues_contending_for_same_victim_matches_oracle():
    """Cross-queue same-victim contention — the conflict class the
    batched round resolves through its serial tail (and, after the first
    claim dirties round state, the sequential fallback turn): queues qb
    and qc both reclaim from qa's only node.  The queue-order turn
    sequence decides who gets which victim; kernel and oracle must agree
    exactly (evict set AND claimant placements), and the forced-batched
    vs forced-sequential engines must agree bit-for-bit (both claims
    land in one round — the second exercises the batched tail's
    post-claim live-pop path)."""
    import jax

    from kube_arbitrator_tpu.api import TaskStatus
    from kube_arbitrator_tpu.cache.decode import decode_decisions
    from kube_arbitrator_tpu.ops import schedule_cycle
    from kube_arbitrator_tpu.ops.preempt import reclaim_action
    from kube_arbitrator_tpu.oracle import SequentialScheduler

    sim = SimCluster()
    sim.add_queue("qa", weight=1)
    sim.add_queue("qb", weight=1)
    sim.add_queue("qc", weight=1)
    sim.add_node("n1", cpu_milli=4000, memory=8 * GB)
    ja = sim.add_job("a", queue="qa", creation_ts=1)  # no gang floor
    for i in range(4):
        sim.add_task(ja, 1000, GB, status=TaskStatus.RUNNING, node="n1",
                     name=f"a-r{i}", priority=i)
    jb = sim.add_job("b", queue="qb", min_available=1, creation_ts=2)
    sim.add_task(jb, 1000, GB, name="b-p0")
    jc = sim.add_job("c", queue="qc", min_available=1, creation_ts=3)
    sim.add_task(jc, 1000, GB, name="c-p0")

    snap = build_snapshot(sim.cluster)
    dec = schedule_cycle(snap.tensors, actions=("reclaim",))
    binds, evicts = decode_decisions(snap, dec)
    oracle = SequentialScheduler(sim.cluster).run_cycle(actions=("reclaim",))

    k_ev = sorted(e.task_uid for e in evicts)
    assert k_ev == sorted(oracle.evicts)
    assert len(k_ev) == 2  # one claim per queue, distinct victims
    ts = np.asarray(dec.task_status)
    pre = np.asarray(snap.tensors.task_status)
    k_pipe = {
        snap.index.tasks[i].uid
        for i in np.nonzero(
            (ts == int(TaskStatus.PIPELINED)) & (pre == int(TaskStatus.PENDING))
        )[0]
    }
    assert k_pipe == set(oracle.pipelined)
    assert k_pipe == {"b-p0", "c-p0"}

    # the same contention case at the kernel level: forced round-batched
    # vs forced sequential canon must agree bit-for-bit (both queues'
    # claims land in one round — the second claim exercises the batched
    # tail's post-claim sequential fallback)
    tiers, sess, state = _open(snap.tensors)
    bat = jax.jit(
        lambda st, se, s: reclaim_action(st, se, s, tiers, turn_batch=True)
    )(snap.tensors, sess, state)
    seq = jax.jit(
        lambda st, se, s: reclaim_action(st, se, s, tiers, turn_batch=False)
    )(snap.tensors, sess, state)
    _assert_state_equal(bat, seq, "two-queue same-victim reclaim")
    # the optimistic engine sees BOTH queues claim in its first window:
    # the second claim is the canonical conflict — discarded, counted,
    # and re-derived in the continuation window, leaving decisions
    # identical and exactly one conflict on the books
    opt = jax.jit(
        lambda st, se, s: reclaim_action(
            st, se, s, tiers, turn_batch="optimistic"
        )
    )(snap.tensors, sess, state)
    _assert_state_equal(opt, seq, "two-queue same-victim reclaim (optimistic)")
    assert int(opt.claim_conflicts) >= 1, (
        "the contending second claim must be discarded as a conflict"
    )


def test_optimistic_action_degrades_when_engine_illegal():
    """A conf-selected ``reclaim_optimistic`` on a pack the engine is
    illegal for (pod affinity with predicates on) must degrade to the
    decision-identical default reclaim path, never raise — the
    previously test-only turn_batch ValueError is reachable from YAML
    now, so the registered action carries its own auto gate."""
    from kube_arbitrator_tpu.api import PodAffinityTerm, TaskStatus
    from kube_arbitrator_tpu.framework import Scheduler
    from kube_arbitrator_tpu.framework.conf import load_conf
    from kube_arbitrator_tpu.ops.preempt import reclaim_engine_fallback_reason

    tiers_yaml = (
        "tiers:\n"
        "- plugins:\n"
        "  - name: priority\n"
        "  - name: gang\n"
        "- plugins:\n"
        "  - name: drf\n"
        "  - name: predicates\n"
        "  - name: proportion\n"
    )

    def mk():
        sim = SimCluster()
        sim.add_queue("q", weight=1)
        for i in range(2):
            sim.add_node(f"n{i}", cpu_milli=4000, memory=8 * GB,
                         labels={"zone": f"z{i}"})
        j0 = sim.add_job("leader", queue="q")
        sim.add_task(j0, 100, 0, name="lead", status=TaskStatus.RUNNING,
                     node="n0", labels={"app": "store"})
        j1 = sim.add_job("follower", queue="q")
        sim.add_task(
            j1, 100, 0, name="f1",
            affinity=[PodAffinityTerm(match_labels=(("app", "store"),),
                                      topology_key="zone")],
        )
        return sim

    sim = mk()
    conf = load_conf(
        'actions: "reclaim_optimistic, allocate, backfill"\n' + tiers_yaml
    )
    st = build_snapshot(sim.cluster).tensors
    assert reclaim_engine_fallback_reason(st, conf.tiers) == "pod_affinity"
    Scheduler(sim, config=conf).run(max_cycles=2, until_idle=False)
    ref = mk()
    ref_conf = load_conf(
        'actions: "reclaim, allocate, backfill"\n' + tiers_yaml
    )
    Scheduler(ref, config=ref_conf).run(max_cycles=2, until_idle=False)
    bound = lambda s: {
        t.uid: t.node_name
        for j in s.cluster.jobs.values() for t in j.tasks.values()
    }
    assert bound(sim) == bound(ref)


@pytest.mark.slow  # tier-1 keeps the kernel-level soak; the PERF_SMOKE
# lane runs this full-loop matrix (deploy/check.sh runs the file unfiltered)
def test_optimistic_reclaim_loop_matches_default_over_seed_matrix():
    """End-to-end opt-in: a conf selecting ``reclaim_optimistic`` runs
    the full scheduler loop over an 8-seed matrix of evictive worlds and
    must produce the SAME bind/evict stream as the default conf — the
    optimistic commit gate discards conflicted speculation, it never
    changes a committed decision (and the model-level invariants — one
    node per task, no double bind — hold because the streams are
    equal)."""
    from kube_arbitrator_tpu.framework import Scheduler
    from kube_arbitrator_tpu.framework.conf import load_conf

    conf = lambda action: load_conf(
        f'actions: "{action}, allocate, backfill, preempt"\n'
        "tiers:\n"
        "- plugins:\n"
        "  - name: priority\n"
        "  - name: gang\n"
        "- plugins:\n"
        "  - name: drf\n"
        "  - name: predicates\n"
        "  - name: proportion\n"
    )
    mk = lambda seed: generate_cluster(
        num_nodes=16, num_jobs=12, tasks_per_job=3, num_queues=4,
        seed=seed, node_cpu_milli=4000, node_memory=8 * GB,
        running_fraction=0.6,
    )
    bound = lambda sim: {
        t.uid: (t.node_name, t.status)
        for j in sim.cluster.jobs.values()
        for t in j.tasks.values()
    }
    evicted_any = False
    for seed in range(8):
        sim_opt, sim_ref = mk(seed), mk(seed)
        s_opt = Scheduler(sim_opt, config=conf("reclaim_optimistic"))
        s_ref = Scheduler(sim_ref, config=conf("reclaim"))
        s_opt.run(max_cycles=3, until_idle=False)
        s_ref.run(max_cycles=3, until_idle=False)
        assert bound(sim_opt) == bound(sim_ref), f"seed {seed} diverged"
        evicted_any = evicted_any or any(s.evicts for s in s_ref.history)
    assert evicted_any, "vacuous matrix: no seed exercised reclaim/preempt"


def test_q512_preempt_turn_bound_is_active_count():
    """The traced trip bound: a q512-shaped world where exactly k queues
    hold a (claimant, victim-job) pair pays k turns per preempt round —
    the round gate (the product's own trip bound, `_round_gate`) must
    admit exactly those k queues, not all 512."""
    import jax

    from kube_arbitrator_tpu.api import TaskStatus
    from kube_arbitrator_tpu.ops.preempt import (
        RUNNING,
        _build_view,
        _entry_qualify,
        _round_gate,
    )

    k = 6
    sim = SimCluster()
    for qi in range(512):
        sim.add_queue(f"q{qi}")
    for ni in range(64):
        sim.add_node(f"n{ni}", cpu_milli=4000, memory=8 * GB)
    # k contended queues: a victim job (running, no gang floor) + a
    # pending claimant job; the rest get one idle pending job each
    for qi in range(512):
        if qi < k:
            jv = sim.add_job(f"v{qi}", queue=f"q{qi}", creation_ts=1)
            for t in range(2):
                sim.add_task(jv, 1000, GB, status=TaskStatus.RUNNING,
                             node=f"n{qi % 64}", name=f"v{qi}-r{t}")
            jc = sim.add_job(f"c{qi}", queue=f"q{qi}", min_available=1,
                             creation_ts=2)
            sim.add_task(jc, 1000, GB, name=f"c{qi}-p0")
        else:
            j = sim.add_job(f"j{qi}", queue=f"q{qi}", min_available=1)
            sim.add_task(j, 1000, GB, name=f"j{qi}-p0")

    st = build_snapshot(sim.cluster).tensors
    tiers, sess, state = _open(st)
    running0 = (
        (state.task_status == RUNNING) & st.task_valid & (state.task_node >= 0)
    )
    qual = jax.jit(lambda st, se, s, r: _entry_qualify(st, se, s, r))(
        st, sess, state, running0
    )
    view = jax.jit(lambda st, s: _build_view(st, s, qual, st.num_tasks))(st, state)
    gate = jax.jit(lambda st, se, s: _round_gate(st, se, s, "preempt", view))(
        st, sess, state
    )
    assert int(np.asarray(gate).sum()) == k, (
        "preempt round gate must admit exactly the contended queues"
    )


def test_pruned_allocate_native_writebacks_match_jnp():
    """The production pairing the soak leaves untested: feasibility-pruned
    panels with the NATIVE i32/f32 scatter writebacks (ops/native
    kat_scatter_add_i32 et al).  On a host-CPU deployment with
    N >= 8*PRUNE_FLOOR both switch on together, so the pruned+native leg
    must be bit-identical to pruned+jnp AND to the unpruned sequential
    reference on a world that exercises real contention."""
    import jax

    from kube_arbitrator_tpu.ops.allocate import allocate_action
    from kube_arbitrator_tpu.ops.native import segsum

    if not segsum.available():
        import pytest

        pytest.skip("native FFI kernels unavailable on this host")

    sim = _world(8, 0)
    st = build_snapshot(sim.cluster).tensors
    tiers, sess, state = _open(st)
    for best_effort in (False, True):
        legs = {
            "native": allocate_action(
                st, sess, state, tiers, best_effort_pass=best_effort,
                turn_batch=True, prune=True, prune_floor=1, native_ops=True,
            ),
            "jnp": allocate_action(
                st, sess, state, tiers, best_effort_pass=best_effort,
                turn_batch=True, prune=True, prune_floor=1, native_ops=False,
            ),
            "seq": allocate_action(
                st, sess, state, tiers, best_effort_pass=best_effort,
                turn_batch=False,
            ),
        }
        name = "backfill" if best_effort else "allocate"
        _assert_state_equal(legs["native"], legs["jnp"], f"{name} native-vs-jnp")
        _assert_state_equal(legs["native"], legs["seq"], f"{name} native-vs-seq")
        state = legs["native"]


def test_round_gate_parity_with_overflow_turns(monkeypatch):
    """The regime the soak's worlds keep small: more simultaneously
    active queues than the selection panel.  Overflow turns run the full
    sequential body and never refresh their carried verdict slots, so a
    queue re-entering the panel in a gated round after a commit must NOT
    reuse pre-commit verdicts just because its selection matches the
    stale carried one — the per-queue `vic_valid` carry forces the
    recompute.  TURN_PANEL is pinned to 2 so every q=8 world exercises
    overflow + panel churn; gate-on must stay bit-identical to the
    sequential loop."""
    import jax

    from kube_arbitrator_tpu.ops import preempt as pre

    monkeypatch.setattr(pre, "TURN_PANEL", 2)
    tiers = SchedulerConfig.default().tiers
    for seed in (0, 1, 2):
        sim = _world(8, seed)
        st = build_snapshot(sim.cluster).tensors
        _, sess, state = _open(st)
        gate_on = jax.jit(
            lambda st, se, s: pre.preempt_action(
                st, se, s, tiers, turn_batch=True, round_gate=True
            )
        )(st, sess, state)
        seq = jax.jit(
            lambda st, se, s: pre.preempt_action(st, se, s, tiers, turn_batch=False)
        )(st, sess, state)
        _assert_state_equal(gate_on, seq, f"overflow gate-on seed={seed}")
