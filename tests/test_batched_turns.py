"""Sequential-vs-batched turn parity (the batched turn kernel's contract).

The batched round (`ops/preempt._rounds_batched`, `ops/allocate._round_batched`)
must replay the sequential turn loop's decisions BIT-FOR-BIT — identical
bind/evict streams, identical task->node pairing, identical round counts.
The soak here runs both engines action-for-action over randomized loaded
clusters at q in {8, 64, 512} and asserts every decision-bearing
AllocState field equal after every action; reclaim (inherently
sequential pop-for-pop — its cross-queue verdicts chain turn-to-turn)
is pinned by comparing its two engines (canon-layout vs sorted-space)
the same way, plus a directed two-queues-one-victim-queue oracle case
for the cross-queue contention the batched doctrine excludes.
"""
import dataclasses

import numpy as np
import pytest

from kube_arbitrator_tpu.cache import SimCluster, build_snapshot, generate_cluster
from kube_arbitrator_tpu.framework.conf import SchedulerConfig

GB = 1024**3
FIELDS = (
    "task_status", "task_node", "evicted_for", "job_ready_cnt",
    "group_placed", "job_alloc", "queue_alloc", "node_num_tasks",
)


def _open(st):
    import jax

    from kube_arbitrator_tpu.ops.cycle import open_session

    tiers = SchedulerConfig.default().tiers
    sess, state = jax.jit(lambda s: open_session(s, tiers))(st)
    return tiers, sess, state


def _assert_state_equal(a, b, ctx):
    for f in FIELDS:
        x, y = np.asarray(getattr(a, f)), np.asarray(getattr(b, f))
        assert np.array_equal(x, y), f"{ctx}: {f} diverged"
    assert int(a.rounds) == int(b.rounds), (
        f"{ctx}: round counts diverged ({int(a.rounds)} vs {int(b.rounds)})"
    )


def _world(q, seed):
    # jobs > queues so most queues hold a claimant and a fair share hold
    # two jobs (the phase-1 victim shape); oversubscribed so evictive
    # actions have work
    return generate_cluster(
        num_nodes=48,
        num_jobs=max(12, q + q // 8),
        tasks_per_job=4,
        num_queues=q,
        seed=seed,
        node_cpu_milli=4000,
        node_memory=8 * GB,
        running_fraction=0.5,
    )


@pytest.mark.parametrize("q", [8, 64, 512])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_sequential_vs_batched_decision_soak(q, seed):
    """3 seeds x {q=8, 64, 512} x {reclaim, allocate, backfill, preempt}:
    thread one state through the full action list with the BATCHED
    engines and, stage-by-stage, check the SEQUENTIAL engine from the
    same entry state produces the identical AllocState (bind/evict
    streams ride task_status/task_node/evicted_for) and round count.
    The batched result is threaded forward (the production path)."""
    import jax

    from kube_arbitrator_tpu.ops.allocate import allocate_action
    from kube_arbitrator_tpu.ops.cycle import commit_cycle
    from kube_arbitrator_tpu.ops.preempt import (
        _reclaim_canon,
        _reclaim_fast,
        preempt_action,
    )

    sim = _world(q, seed)
    st = build_snapshot(sim.cluster).tensors
    tiers, sess, state = _open(st)

    # ---- reclaim: canon-layout vs sorted-space engines ----
    canon = jax.jit(
        lambda st, se, s: _reclaim_canon(st, se, s, tiers, 100_000)
    )(st, sess, state)
    fast = jax.jit(
        lambda st, se, s: _reclaim_fast(st, se, s, tiers, 100_000)
    )(st, sess, state)
    _assert_state_equal(canon, fast, f"reclaim q={q} seed={seed}")
    state = canon

    # ---- allocate + backfill: batched (deferred) vs immediate rounds ----
    for best_effort in (False, True):
        name = "backfill" if best_effort else "allocate"
        batched = allocate_action(
            st, sess, state, tiers, best_effort_pass=best_effort, turn_batch=True
        )
        seq = allocate_action(
            st, sess, state, tiers, best_effort_pass=best_effort, turn_batch=False
        )
        _assert_state_equal(batched, seq, f"{name} q={q} seed={seed}")
        state = batched

    # ---- preempt: batched turn kernel vs sequential turn loop ----
    batched = jax.jit(
        lambda st, se, s: preempt_action(st, se, s, tiers, turn_batch=True)
    )(st, sess, state)
    seq = jax.jit(
        lambda st, se, s: preempt_action(st, se, s, tiers, turn_batch=False)
    )(st, sess, state)
    _assert_state_equal(batched, seq, f"preempt q={q} seed={seed}")
    state = batched

    # the run must have exercised the evictive machinery, or the parity
    # above is vacuous (placements may land as PIPELINED claims rather
    # than committed binds when the claimant gang stays short)
    dec = jax.jit(commit_cycle)(st, sess, state)
    from kube_arbitrator_tpu.api import TaskStatus

    ts = np.asarray(dec.task_status)
    placed = int(np.asarray(dec.bind_mask).sum()) + int(
        (ts == int(TaskStatus.PIPELINED)).sum()
    )
    assert int(np.asarray(dec.evict_mask).sum()) > 0, "vacuous soak: no evictions"
    assert placed > 0, "vacuous soak: nothing placed or pipelined"


def test_two_queues_contending_for_same_victim_matches_oracle():
    """Cross-queue same-victim contention — the conflict class the
    batched doctrine leaves to reclaim's sequential pop-for-pop: queues
    qb and qc both reclaim from qa's only node.  The queue-order turn
    sequence decides who gets which victim; kernel and oracle must agree
    exactly (evict set AND claimant placements)."""
    from kube_arbitrator_tpu.api import TaskStatus
    from kube_arbitrator_tpu.cache.decode import decode_decisions
    from kube_arbitrator_tpu.ops import schedule_cycle
    from kube_arbitrator_tpu.oracle import SequentialScheduler

    sim = SimCluster()
    sim.add_queue("qa", weight=1)
    sim.add_queue("qb", weight=1)
    sim.add_queue("qc", weight=1)
    sim.add_node("n1", cpu_milli=4000, memory=8 * GB)
    ja = sim.add_job("a", queue="qa", creation_ts=1)  # no gang floor
    for i in range(4):
        sim.add_task(ja, 1000, GB, status=TaskStatus.RUNNING, node="n1",
                     name=f"a-r{i}", priority=i)
    jb = sim.add_job("b", queue="qb", min_available=1, creation_ts=2)
    sim.add_task(jb, 1000, GB, name="b-p0")
    jc = sim.add_job("c", queue="qc", min_available=1, creation_ts=3)
    sim.add_task(jc, 1000, GB, name="c-p0")

    snap = build_snapshot(sim.cluster)
    dec = schedule_cycle(snap.tensors, actions=("reclaim",))
    binds, evicts = decode_decisions(snap, dec)
    oracle = SequentialScheduler(sim.cluster).run_cycle(actions=("reclaim",))

    k_ev = sorted(e.task_uid for e in evicts)
    assert k_ev == sorted(oracle.evicts)
    assert len(k_ev) == 2  # one claim per queue, distinct victims
    ts = np.asarray(dec.task_status)
    pre = np.asarray(snap.tensors.task_status)
    k_pipe = {
        snap.index.tasks[i].uid
        for i in np.nonzero(
            (ts == int(TaskStatus.PIPELINED)) & (pre == int(TaskStatus.PENDING))
        )[0]
    }
    assert k_pipe == set(oracle.pipelined)
    assert k_pipe == {"b-p0", "c-p0"}


def test_q512_preempt_turn_bound_is_active_count():
    """The traced trip bound: a q512-shaped world where exactly k queues
    hold a (claimant, victim-job) pair pays k turns per preempt round —
    the round gate (the product's own trip bound, `_round_gate`) must
    admit exactly those k queues, not all 512."""
    import jax

    from kube_arbitrator_tpu.api import TaskStatus
    from kube_arbitrator_tpu.ops.preempt import (
        RUNNING,
        _build_view,
        _entry_qualify,
        _round_gate,
    )

    k = 6
    sim = SimCluster()
    for qi in range(512):
        sim.add_queue(f"q{qi}")
    for ni in range(64):
        sim.add_node(f"n{ni}", cpu_milli=4000, memory=8 * GB)
    # k contended queues: a victim job (running, no gang floor) + a
    # pending claimant job; the rest get one idle pending job each
    for qi in range(512):
        if qi < k:
            jv = sim.add_job(f"v{qi}", queue=f"q{qi}", creation_ts=1)
            for t in range(2):
                sim.add_task(jv, 1000, GB, status=TaskStatus.RUNNING,
                             node=f"n{qi % 64}", name=f"v{qi}-r{t}")
            jc = sim.add_job(f"c{qi}", queue=f"q{qi}", min_available=1,
                             creation_ts=2)
            sim.add_task(jc, 1000, GB, name=f"c{qi}-p0")
        else:
            j = sim.add_job(f"j{qi}", queue=f"q{qi}", min_available=1)
            sim.add_task(j, 1000, GB, name=f"j{qi}-p0")

    st = build_snapshot(sim.cluster).tensors
    tiers, sess, state = _open(st)
    running0 = (
        (state.task_status == RUNNING) & st.task_valid & (state.task_node >= 0)
    )
    qual = jax.jit(lambda st, se, s, r: _entry_qualify(st, se, s, r))(
        st, sess, state, running0
    )
    view = jax.jit(lambda st, s: _build_view(st, s, qual, st.num_tasks))(st, state)
    gate = jax.jit(lambda st, se, s: _round_gate(st, se, s, "preempt", view))(
        st, sess, state
    )
    assert int(np.asarray(gate).sum()) == k, (
        "preempt round gate must admit exactly the contended queues"
    )
