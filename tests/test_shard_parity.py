"""Sharded cluster plane: sharded-vs-dense decision parity + the
shard_map building blocks' dense twins + the sharded arena plane.

The contract the whole plane rests on: decisions computed over the
node-partitioned mesh are BIT-IDENTICAL to the dense program — same
tiebreak key (global node ordinal), same bind/evict streams, same audit
aux.  The full acceptance soak (3 seeds × q{8,64,512} × shard counts
{1,2,8}, full actions) is marked slow and runs in the shard-smoke CI
lane; a 4-point sample of the same matrix runs in tier-1.
"""
import dataclasses

import jax
import numpy as np
import pytest

from kube_arbitrator_tpu.cache import build_snapshot, generate_cluster
from kube_arbitrator_tpu.framework.conf import load_conf
from kube_arbitrator_tpu.ops import schedule_cycle
from kube_arbitrator_tpu.parallel import (
    ShardLayout,
    ShardedDecider,
    make_mesh,
    shard_snapshot,
    sharded_argmin_node,
    sharded_node_capacity,
    sharded_prefix_fill,
    sharded_schedule_cycle,
    sharded_victim_panels,
    shard_feasible_panel,
    shard_fit_panel,
)

GB = 1024**3

FULL_CONF = load_conf(
    'actions: "reclaim, allocate, backfill, preempt"\n'
    "tiers:\n"
    "- plugins:\n"
    "  - name: priority\n"
    "  - name: gang\n"
    "- plugins:\n"
    "  - name: drf\n"
    "  - name: predicates\n"
    "  - name: proportion\n"
)

# Every decision-bearing AND audit-aux field: the parity bar is the whole
# reply pack, not just the bind stream.
DEC_FIELDS = (
    "task_node", "task_status", "bind_mask", "evict_mask", "job_ready",
    "unready_alloc", "evict_claimant", "evict_phase", "evict_round",
    "bind_idx", "bind_node", "evict_idx", "bind_count", "evict_count",
)


def _world(q, seed):
    return generate_cluster(
        num_nodes=48,
        num_jobs=max(12, q + q // 8),
        tasks_per_job=4,
        num_queues=q,
        seed=seed,
        node_cpu_milli=4000,
        node_memory=8 * GB,
        running_fraction=0.5,
    )


def _assert_identical(dense, sharded, ctx):
    for f in DEC_FIELDS:
        a, b = np.asarray(getattr(dense, f)), np.asarray(getattr(sharded, f))
        assert np.array_equal(a, b), f"{ctx}: {f} diverged"


def _run_parity(q, seed, shards):
    sim = _world(q, seed)
    snap = build_snapshot(sim.cluster)
    dense = schedule_cycle(
        snap.tensors, tiers=FULL_CONF.tiers, actions=FULL_CONF.actions
    )
    mesh = make_mesh(jax.devices()[:shards])
    sh = sharded_schedule_cycle(
        snap.tensors, mesh=mesh, tiers=FULL_CONF.tiers,
        actions=FULL_CONF.actions,
    )
    _assert_identical(dense, sh, f"q={q} seed={seed} shards={shards}")
    assert int(dense.bind_count) + int(dense.evict_count) > 0, (
        "vacuous parity: the cycle decided nothing"
    )


@pytest.mark.slow
@pytest.mark.parametrize("shards", [1, 2, 8])
@pytest.mark.parametrize("q", [8, 64, 512])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_parity_soak_full_matrix(q, seed, shards):
    """The acceptance soak: 3 seeds × q{8,64,512} × shard counts
    {1,2,8}, full actions, whole reply pack bit-identical."""
    _run_parity(q, seed, shards)


@pytest.mark.parametrize(
    "q,seed,shards", [(8, 0, 8), (64, 1, 2), (512, 2, 8), (8, 2, 1)]
)
def test_parity_sample(q, seed, shards):
    """Tier-1 sample of the soak matrix (the full matrix is the slow
    shard-smoke lane's job)."""
    _run_parity(q, seed, shards)


# ---------------------------------------------------------------------------
# shard_map building blocks vs their dense twins


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest forces 8 virtual devices"
    return make_mesh()


@pytest.fixture(scope="module")
def opened():
    from kube_arbitrator_tpu.ops.cycle import open_session

    sim = generate_cluster(
        num_nodes=64, num_jobs=12, tasks_per_job=8, num_queues=3, seed=3,
        running_fraction=0.4,
    )
    st = build_snapshot(sim.cluster).tensors
    sess, state = jax.jit(lambda s: open_session(s, FULL_CONF.tiers))(st)
    return st, sess, state


def test_feasible_panel_matches_dense(mesh, opened):
    """shard_feasible_panel == _prune_feasible: both run the SAME
    _feasible_cells, one on shard-local blocks, one full-width."""
    import jax.numpy as jnp

    from kube_arbitrator_tpu.ops.allocate import _class_minreq, _prune_feasible

    st, sess, state = opened
    dense = _prune_feasible(st, state, FULL_CONF.tiers, False)
    stg = shard_snapshot(st, mesh)
    sh = shard_feasible_panel(
        mesh, st.class_fit, stg.node_klass, stg.node_valid, stg.node_unsched,
        True, _class_minreq(st),
        jax.device_put(np.maximum(
            np.asarray(state.node_idle), np.asarray(state.node_releasing)
        )),
    )
    np.testing.assert_array_equal(np.asarray(dense), np.asarray(sh))


def test_fit_panel_is_per_shard_compaction(mesh, opened):
    """shard_fit_panel: shard s's panel block == _compact_rows of shard
    s's feasibility columns, offset into GLOBAL node ordinals."""
    import jax.numpy as jnp

    from kube_arbitrator_tpu.ops.allocate import _compact_rows, _prune_feasible

    st, sess, state = opened
    feas = _prune_feasible(st, state, FULL_CONF.tiers, False)
    N, S, NC = st.num_nodes, 8, 4
    blk = N // S
    pan = np.asarray(shard_fit_panel(mesh, jax.device_put(
        np.asarray(feas),
        jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec(None, "nodes")),
    ), NC))
    feas_np = np.asarray(feas)
    for s in range(S):
        ref = np.asarray(
            _compact_rows(jnp.asarray(feas_np[:, s * blk:(s + 1) * blk]), NC)
        )
        ref_g = np.where(ref < blk, ref + s * blk, N)
        np.testing.assert_array_equal(pan[:, s * NC:(s + 1) * NC], ref_g)


def test_node_capacity_matches_dense(mesh, opened):
    import jax.numpy as jnp

    from kube_arbitrator_tpu.ops.allocate import _node_capacity

    st, sess, state = opened
    req = st.group_resreq[0]
    ph = st.node_max_tasks - st.node_num_tasks
    dense = _node_capacity(
        state.node_idle, req, st.node_valid, ph, jnp.array(False)
    )
    sh = sharded_node_capacity(
        mesh, jax.device_put(np.asarray(state.node_idle)), req,
        st.node_valid, ph, jnp.array(False),
    )
    np.testing.assert_array_equal(np.asarray(dense), np.asarray(sh))


def test_prefix_fill_matches_dense_cumsum(mesh, opened):
    """The collective-offset prefix fill == the dense jnp.cumsum fill for
    every budget regime (zero, partial, boundary, unbounded)."""
    import jax.numpy as jnp

    from kube_arbitrator_tpu.ops.allocate import _node_capacity

    st, sess, state = opened
    req = st.group_resreq[0]
    ph = st.node_max_tasks - st.node_num_tasks
    k = np.asarray(
        _node_capacity(state.node_idle, req, st.node_valid, ph, jnp.array(False))
    )
    for budget in (0, 3, 17, int(k.sum()), 10**6):
        cum = np.cumsum(k)
        placed = min(budget, int(cum[-1]))
        p_ref = np.clip(placed - (cum - k), 0, k)
        p, pl = sharded_prefix_fill(mesh, jnp.asarray(k), jnp.int32(budget))
        assert int(pl) == placed
        np.testing.assert_array_equal(np.asarray(p), p_ref)


def test_argmin_matches_dense_lex_argmin(mesh):
    """The cross-shard argmin (shard winners + global-ordinal tiebreak)
    picks exactly the dense lex_argmin's first-set-index winner."""
    import jax.numpy as jnp

    from kube_arbitrator_tpu.ops.common import lex_argmin

    N = 128
    rng = np.random.default_rng(0)
    for trial in range(8):
        keys = [
            jnp.asarray(rng.integers(0, 4, N).astype(np.float32))
            for _ in range(3)
        ]
        mask = jnp.asarray(rng.random(N) < (0.02 if trial < 4 else 0.4))
        i_ref, any_ref = lex_argmin(keys, mask)
        i_sh, any_sh = sharded_argmin_node(mesh, keys, mask)
        assert bool(any_ref) == bool(any_sh)
        if bool(any_ref):
            assert int(i_ref) == int(i_sh)


def test_victim_panels_match_dense_scatter(mesh, opened):
    """Shard-local victim eligibility/sum panels == the dense one-scatter
    panels (counts exact; float sums fold the same contributors in the
    same task order)."""
    from kube_arbitrator_tpu.api.types import TaskStatus

    st, sess, state = opened
    N = st.num_nodes
    tn, tv = np.asarray(st.task_node), np.asarray(st.task_valid)
    ts, tr = np.asarray(st.task_status), np.asarray(st.task_resreq)
    run = (ts == int(TaskStatus.RUNNING)) & tv & (tn >= 0)
    counts_ref = np.bincount(tn[run], minlength=N)
    sums_ref = np.zeros((N, tr.shape[1]), np.float32)
    for i in np.nonzero(run)[0]:
        sums_ref[tn[i]] += tr[i]
    c, s = sharded_victim_panels(
        mesh, st.node_valid, st.task_node, st.task_valid, st.task_status,
        st.task_resreq,
    )
    np.testing.assert_array_equal(np.asarray(c), counts_ref)
    np.testing.assert_array_equal(np.asarray(s), sums_ref)


# ---------------------------------------------------------------------------
# the sharded arena plane (per-shard diffs / uploads / verify)


def test_sharded_arena_loop_matches_dense_and_uploads_per_shard():
    """A Scheduler loop on arena + ShardedDecider: (a) placements equal
    the dense loop's; (b) after a small actuation delta, the sharded
    resident re-uploads ONLY the shards owning dirty node rows; (c) the
    byte-identity verifier stays clean."""
    from kube_arbitrator_tpu.framework import Scheduler

    mk = lambda: generate_cluster(
        num_nodes=32, num_jobs=10, tasks_per_job=6, num_queues=4, seed=11,
        running_fraction=0.3,
    )
    sim_a, sim_b = mk(), mk()
    sched = Scheduler(sim_a, decider=ShardedDecider(8), arena=True)
    sched.run(max_cycles=1, until_idle=False)
    arena = sched.arena
    sr = arena._sharded_resident
    assert sr.last_mode == "full" and sr.last_shard_uploads > 0
    sched.run(max_cycles=1, until_idle=False)
    # cycle 2's diff carries cycle 1's binds: node rows changed on SOME
    # shards only -> shard_delta mode with a strict subset re-uploaded
    layout = ShardLayout(8, arena._shipped["node_valid"].shape[0])
    dirty = {s for s, n in arena.shard_dirty_rows(layout).items() if n}
    assert sr.last_mode == "shard_delta", sr.last_mode
    # every node-sharded field re-uploads at most the dirty shard set
    n_node_fields = 9  # len(parallel.mesh._NODE_SHARDED_FIELDS)
    assert sr.last_shard_uploads <= len(dirty) * n_node_fields
    assert 0 < len(dirty) < 8, dirty
    Scheduler(sim_b).run(max_cycles=2, until_idle=False)
    bound = lambda sim: {
        t.uid: t.node_name
        for j in sim.cluster.jobs.values()
        for t in j.tasks.values()
    }
    assert bound(sim_a) == bound(sim_b)
    arena.verify()


def test_sharded_verify_blames_owning_shard():
    """A lost delta (corruption) in one partition: the verifier fires
    AND names exactly the owning shard."""
    from kube_arbitrator_tpu.cache.arena import ArenaDivergence
    from kube_arbitrator_tpu.framework import Scheduler

    sim = generate_cluster(
        num_nodes=24, num_jobs=6, tasks_per_job=4, num_queues=2, seed=5
    )
    sched = Scheduler(sim, decider=ShardedDecider(8), arena=True)
    sched.run(max_cycles=1, until_idle=False)
    arena = sched.arena
    layout = ShardLayout(8, arena._shipped["node_valid"].shape[0])
    row = 5 * layout.block + 2
    arena.corrupt(
        "node_idle", row, np.array([9e6, 9e6, 9e6, 9e6], np.float32)
    )
    with pytest.raises(ArenaDivergence, match=r"\[shards \[5\]\]"):
        arena.verify()


def test_sharded_decider_emits_shard_metrics():
    from kube_arbitrator_tpu.framework import Scheduler
    from kube_arbitrator_tpu.utils.metrics import metrics

    sim = generate_cluster(
        num_nodes=16, num_jobs=4, tasks_per_job=4, num_queues=2, seed=1
    )
    Scheduler(sim, decider=ShardedDecider(8), arena=True).run(
        max_cycles=1, until_idle=False
    )
    text = metrics().render()
    assert 'shard_valid_nodes{shard="0"}' in text
    assert "shard_skew" in text
    assert 'shard_uploads_total{shard="7"}' in text


def test_pack_meta_decode_caps_flow_through_sharded_decider():
    """Per-tenant decode caps (PackMeta.decode_caps) reach the sharded
    program: a tiny cap forces the compact lists to that width and the
    dense decode fallback on overflow."""
    from kube_arbitrator_tpu.cache.arena import SnapshotArena
    from kube_arbitrator_tpu.framework import Scheduler

    sim = generate_cluster(
        num_nodes=16, num_jobs=6, tasks_per_job=4, num_queues=2, seed=9
    )
    arena = SnapshotArena(sim, decode_caps=(2, 1))
    sched = Scheduler(sim, decider=ShardedDecider(8), arena=arena)
    sched.run(max_cycles=1, until_idle=False)
    # the run actuated through the dense fallback; the caps sized the lists
    assert arena.pack_meta.decode_caps == (2, 1)


def test_arena_with_non_dividing_mesh_falls_back_to_host_pack():
    """A mesh whose size doesn't divide the 128-bucketed node axis: the
    per-shard resident is unavailable, so upload hands the decider the
    host pack (it re-pads + shards itself) — the loop still runs and
    matches the dense loop instead of crashing every cycle."""
    from kube_arbitrator_tpu.framework import Scheduler

    mk = lambda: generate_cluster(
        num_nodes=20, num_jobs=6, tasks_per_job=4, num_queues=2, seed=13
    )
    sim_s, sim_d = mk(), mk()
    sched = Scheduler(sim_s, decider=ShardedDecider(3), arena=True)
    sched.run(max_cycles=2, until_idle=False)
    assert not sched.arena.mesh_divides(
        __import__("kube_arbitrator_tpu.parallel", fromlist=["make_mesh"])
        .make_mesh(jax.devices()[:3])
    )
    Scheduler(sim_d).run(max_cycles=2, until_idle=False)
    bound = lambda sim: {
        t.uid: t.node_name
        for j in sim.cluster.jobs.values()
        for t in j.tasks.values()
    }
    assert bound(sim_s) == bound(sim_d)
