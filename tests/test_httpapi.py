"""HTTP apiserver shim + apiserver-backed leader election + live churn.

Round-4 'done' criteria:

* the live plane dials a URL: LiveCache over HttpApiClient schedules
  end-to-end against serve_api on localhost (the client-go seam,
  cache.go:202-223);
* two schedulers contend through one apiserver ConfigMap resourcelock
  (server.go:102-125); only the leaseholder actuates, lease-loss is fatal;
* the dynamic taint/untaint and eviction-event e2e scenarios (sim-proven
  in round 2) run through the WATCH plane (util.go:746-800, :419-438).
"""
import numpy as np
import pytest

from kube_arbitrator_tpu.api import TaskStatus
from kube_arbitrator_tpu.cache import FakeApiServer, LiveCache
from kube_arbitrator_tpu.cache.fakeapi import ApiError
from kube_arbitrator_tpu.cache.httpapi import HttpApiClient, serve_api
from kube_arbitrator_tpu.framework import ApiLeaderElector, Scheduler
from kube_arbitrator_tpu.framework.conf import load_conf
from kube_arbitrator_tpu.options import reset_options

from test_live_cache import make_node, make_pod, make_podgroup, seed_gang_cluster

FULL_CONF = (
    'actions: "reclaim, allocate, backfill, preempt"\n'
    "tiers:\n"
    "- plugins:\n"
    "  - name: priority\n"
    "  - name: gang\n"
    "- plugins:\n"
    "  - name: drf\n"
    "  - name: predicates\n"
    "  - name: proportion\n"
)


@pytest.fixture(autouse=True)
def _fresh_options():
    reset_options()
    yield
    reset_options()


@pytest.fixture()
def http_api():
    api = FakeApiServer()
    server, thread, url = serve_api(api)
    yield api, HttpApiClient(url)
    server.shutdown()


# ---------------------------------------------------------------- HTTP verbs


def test_http_crud_and_watch_roundtrip(http_api):
    api, client = http_api
    client.create("nodes", make_node("n0"))
    items, rv = client.list("nodes")
    assert len(items) == 1 and rv >= 1
    assert client.get("nodes", "", "n0")["metadata"]["name"] == "n0"
    assert client.get("nodes", "", "missing") is None

    client.create("pods", make_pod("p0"))
    events = client.watch_all(0)
    assert [(r, t) for _, r, t, _ in events] == [("nodes", "ADDED"), ("pods", "ADDED")]

    client.bind_pod("default", "p0", "n0")
    pod = client.get("pods", "default", "p0")
    assert pod["spec"]["nodeName"] == "n0"
    # kubelet emulation produced the Running MODIFIED event
    assert pod["status"]["phase"] == "Running"

    with pytest.raises(ApiError) as ei:
        client.bind_pod("default", "p0", "n0")
    assert ei.value.status == 409  # already bound

    client.evict_pod("default", "p0")
    assert client.get("pods", "default", "p0") is None


def test_http_conditional_update_and_delete(http_api):
    api, client = http_api
    obj = client.create("configmaps", {"metadata": {"namespace": "ns", "name": "cm"}})
    rv = obj["metadata"]["resourceVersion"]
    obj["data"] = {"k": "1"}
    upd = client.update("configmaps", obj, expect_rv=rv)
    with pytest.raises(ApiError) as ei:
        client.update("configmaps", obj, expect_rv=rv)  # stale rv
    assert ei.value.status == 409
    with pytest.raises(ApiError) as ei:
        client.delete("configmaps", "ns", "cm", expect_rv=rv)  # stale rv
    assert ei.value.status == 409
    client.delete("configmaps", "ns", "cm",
                  expect_rv=upd["metadata"]["resourceVersion"])
    assert client.get("configmaps", "ns", "cm") is None


def test_scheduler_end_to_end_over_http(http_api):
    """The round-4 'done' criterion: LiveCache scheduling end-to-end over
    localhost HTTP — list/watch in, binds/status out, watch round-trip."""
    api, client = http_api
    seed_gang_cluster(api, n_pods=4)
    live = LiveCache(client)  # the cache only ever speaks HTTP
    sched = Scheduler(live)

    result = sched.run_once()
    assert len(result.binds) == 4
    for i in range(4):
        pod = api.get("pods", "default", f"p{i}")
        assert pod["spec"]["nodeName"] in ("n0", "n1")
    assert api.get("podgroups", "default", "pg1")["status"]["phase"] == "Running"

    live.sync()
    job = live.cluster.jobs["default/pg1"]
    assert all(t.status == TaskStatus.RUNNING for t in job.tasks.values())
    assert sched.run_once().binds == []


def test_http_bind_failure_diverts_to_resync(http_api):
    api, client = http_api
    seed_gang_cluster(api, min_member=1, n_pods=2)
    api.fail_bind_uids = {"uid-default-p0"}
    live = LiveCache(client)
    sched = Scheduler(live)
    sched.run_once()
    assert not api.get("pods", "default", "p0")["spec"]["nodeName"]
    assert any(e.kind == "FailedScheduling" for e in live.events)
    api.fail_bind_uids = set()
    sched.run_once()
    assert api.get("pods", "default", "p0")["spec"]["nodeName"]


# ------------------------------------------------- apiserver leader election


def _elector(api, ident, clock):
    return ApiLeaderElector(api, identity=ident, lease_duration_s=15.0,
                            renew_deadline_s=10.0, retry_period_s=1.0,
                            now_fn=lambda: clock[0])


def test_api_lease_first_contender_wins(http_api):
    api, client = http_api
    clock = [0.0]
    a, b = _elector(client, "a", clock), _elector(client, "b", clock)
    assert a.try_acquire()
    assert not b.try_acquire()
    assert a.is_leader and not b.is_leader
    # the lock object is a real ConfigMap through the verbs
    cm = api.get("configmaps", "kube-system", "kube-batch-lock")
    assert "control-plane.alpha.kubernetes.io/leader" in cm["metadata"]["annotations"]


def test_api_lease_renewal_and_stale_takeover(http_api):
    _, client = http_api
    clock = [0.0]
    a, b = _elector(client, "a", clock), _elector(client, "b", clock)
    assert a.try_acquire()
    clock[0] = 8.0
    assert a.renew()
    clock[0] = 16.0
    assert not b.try_acquire()  # b first observes the t=8 record here
    clock[0] = 24.0
    # the record is stale on a's own clock, but b must observe it
    # unchanged for a full lease_duration on ITS clock (client-go
    # observedTime semantics, cross-host skew protection)
    assert not b.try_acquire()
    clock[0] = 32.0
    assert b.try_acquire()  # stale -> usurped
    assert not a.renew() and not a.is_leader  # loss is fatal to a


def test_api_lease_concurrent_cas_single_winner(http_api):
    """Both contenders fetch the same expired lease; only one CAS wins —
    the resourceVersion precondition resolves the race."""
    api, client = http_api
    clock = [0.0]
    a, b = _elector(client, "a", clock), _elector(client, "b", clock)
    assert a.try_acquire()
    clock[0] = 100.0  # lease long dead
    # simulate the interleaving: both read, then both push
    from kube_arbitrator_tpu.framework import LeaseRecord

    def rec(ident):
        return LeaseRecord(holder=ident, acquired_ts=100.0, renew_ts=100.0,
                           lease_duration_s=15.0)

    tok_a, _ = a._fetch()
    tok_b, _ = b._fetch()
    assert a._push(tok_a, rec("a"))
    assert not b._push(tok_b, rec("b"))  # 409 conflict


def test_api_lease_release_is_compare_and_delete(http_api):
    _, client = http_api
    clock = [0.0]
    a, b = _elector(client, "a", clock), _elector(client, "b", clock)
    assert a.try_acquire()
    assert not b.try_acquire()  # b observes a's record at t=0
    # a goes stale; b takes over; a's release must NOT remove b's lease
    clock[0] = 50.0
    tok_a, cur_a = a._fetch()  # a still sees itself as holder
    assert b.try_acquire()  # observed unchanged for 50s > lease_duration
    assert cur_a.holder == "a"
    a._delete(tok_a)  # stale compare-and-delete -> 409, swallowed
    _, cur = b._fetch()
    assert cur is not None and cur.holder == "b"
    assert b.renew()


def test_api_lease_transient_outage_does_not_crash():
    """An unreachable apiserver surfaces as a failed attempt, not an
    exception (client-go tolerance; review finding round 4)."""
    client = HttpApiClient("http://127.0.0.1:1")  # nothing listens
    clock = [0.0]
    el = _elector(client, "a", clock)
    assert not el.try_acquire()
    assert not el.renew()
    el.release()  # no raise


def test_only_leaseholder_actuates(http_api):
    """Two LiveCache schedulers against one apiserver: only the leaseholder
    schedules (server.go:102-125 — RunOrDie gates sched.Run), and losing
    the lease to a usurper is fatal (:119-121)."""
    from kube_arbitrator_tpu.framework import LeaderLost

    api, client = http_api
    seed_gang_cluster(api, n_pods=4)
    clock = [0.0]
    leader_el = _elector(client, "leader", clock)
    standby_el = _elector(client, "standby", clock)
    assert leader_el.try_acquire()
    assert not standby_el.try_acquire()  # standby stays gated

    active = Scheduler(LiveCache(client), elector=leader_el)
    active.run(max_cycles=1)
    bound = [i for i in range(4)
             if api.get("pods", "default", f"p{i}")["spec"]["nodeName"]]
    assert len(bound) == 4

    # leader goes stale; standby usurps; the ex-leader's next run is fatal
    clock[0] = 30.0
    assert standby_el.try_acquire()
    with pytest.raises(LeaderLost):
        active.run(max_cycles=1)


# ------------------------------------------------------- live-plane churn e2e


def test_live_taint_untaint_mid_run(http_api):
    """util.go:746-800 through the WATCH plane: a taint PATCHed onto a node
    between cycles redirects scheduling; untainting restores it."""
    api, client = http_api
    for i in range(3):
        api.create("nodes", make_node(f"n{i}", cpu="4"))
    api.create("queues", {"metadata": {"name": "default"}, "spec": {"weight": 1}})
    api.create("podgroups", make_podgroup("warm", min_member=3))
    for i in range(3):
        api.create("pods", make_pod(f"w{i}", group="warm"))
    live = LiveCache(client)
    sched = Scheduler(live, config=load_conf(FULL_CONF))
    assert len(sched.run_once().binds) == 3

    # taint n2 via the apiserver (strategic-merge patch analog)
    node = client.get("nodes", "", "n2")
    node["spec"]["taints"] = [
        {"key": "test-taint-key", "value": "taint-val", "effect": "NoSchedule"}
    ]
    client.update("nodes", node)
    api.create("podgroups", make_podgroup("after-taint", min_member=1))
    for i in range(6):
        api.create("pods", make_pod(f"a{i}", group="after-taint", cpu="1"))
    for _ in range(4):
        sched.run_once()
    placed = {
        api.get("pods", "default", f"a{i}")["spec"].get("nodeName")
        for i in range(6)
    } - {"", None}
    assert placed and "n2" not in placed

    # untaint: new pods reach n2 again
    node = client.get("nodes", "", "n2")
    node["spec"]["taints"] = []
    client.update("nodes", node)
    api.create("podgroups", make_podgroup("after-untaint", min_member=1))
    for i in range(3):
        api.create("pods", make_pod(f"u{i}", group="after-untaint", cpu="1"))
    for _ in range(4):
        sched.run_once()
    placed3 = {
        api.get("pods", "default", f"u{i}")["spec"].get("nodeName")
        for i in range(3)
    } - {"", None}
    assert "n2" in placed3


def test_live_eviction_detected_via_events(http_api):
    """util.go:419-438 waitTasksEvicted through the watch plane: reclaim
    DELETEs victims at the apiserver, Evict events surface with uids, and
    the deletions flow back through the watch into the model."""
    api, client = http_api
    api.create("nodes", make_node("n0", cpu="4"))
    api.create("queues", {"metadata": {"name": "qa"}, "spec": {"weight": 1}})
    api.create("queues", {"metadata": {"name": "qb"}, "spec": {"weight": 1}})
    api.create("podgroups", make_podgroup("victims", min_member=0, queue="qa"))
    api.create("podgroups", make_podgroup("claimer", min_member=1, queue="qb"))
    for i in range(4):
        api.create("pods", make_pod(f"v{i}", group="victims", cpu="1",
                                    memory="256Mi", node="n0", phase="Running"))
    api.create("pods", make_pod("c0", group="claimer", cpu="1", memory="256Mi"))
    live = LiveCache(client)
    sched = Scheduler(live, config=load_conf(FULL_CONF))
    result = sched.run_once()
    assert len(result.evicts) >= 1
    evict_events = [e for e in live.events if e.kind == "Evict"]
    assert evict_events and all(e.object_uid.startswith("uid-default-v")
                                for e in evict_events)
    live.sync()
    assert len(live.cluster.jobs["default/victims"].tasks) == 4 - len(result.evicts)


# ------------------------------------------------------- bearer-token auth


def test_bearer_token_rejects_unauthenticated_writes():
    """serve_api(token=...) is the authenticated-rest.Config seam
    (app/server.go:51-56): writes AND reads without the credential are
    401, a wrong token is 401, and the full client surface works with
    the right one."""
    api = FakeApiServer()
    server, _, url = serve_api(api, token="s3cret")
    try:
        anon = HttpApiClient(url)
        with pytest.raises(ApiError) as err:
            anon.create("pods", {"metadata": {"namespace": "default", "name": "p0"}})
        assert err.value.status == 401
        with pytest.raises(ApiError) as err:
            anon.list("pods")
        assert err.value.status == 401

        wrong = HttpApiClient(url, token="nope")
        with pytest.raises(ApiError) as err:
            wrong.bind_pod("default", "p0", "n0")
        assert err.value.status == 401

        good = HttpApiClient(url, token="s3cret")
        good.create("nodes", {"metadata": {"name": "n0"},
                              "status": {"allocatable": {"cpu": "4"}}})
        good.create("pods", {"metadata": {"namespace": "default", "name": "p0"}})
        good.bind_pod("default", "p0", "n0")
        assert good.get("pods", "default", "p0")["spec"]["nodeName"] == "n0"
        # the store never saw the unauthenticated create
        items, _ = good.list("pods")
        assert len(items) == 1
    finally:
        server.shutdown()


def test_bearer_token_file_plumbing(tmp_path):
    """token_file mirrors the in-cluster serviceaccount credential path."""
    api = FakeApiServer()
    server, _, url = serve_api(api, token="tok-abc")
    try:
        tf = tmp_path / "token"
        tf.write_text("tok-abc\n")
        client = HttpApiClient(url, token_file=str(tf))
        client.create("queues", {"metadata": {"name": "q1"}, "spec": {"weight": 2}})
        assert client.get("queues", "", "q1")["spec"]["weight"] == 2
    finally:
        server.shutdown()


# ------------------------------------------------------ volume plane (PV/PVC)


def test_zonal_pv_pins_placement_over_http():
    """Directive: PV/PVC/StorageClass ingestion in the live plane
    (cache.go:230-238, :288-306).  A pod whose PVC is bound to a zone-b
    PV must land on the zone-b node even though the zone-a node is
    first-fit, end-to-end over HTTP."""
    api = FakeApiServer()
    server, _, url = serve_api(api)
    try:
        client = HttpApiClient(url)
        na = make_node("na")
        na["metadata"]["labels"]["topology.kubernetes.io/zone"] = "zone-a"
        nb = make_node("nb")
        nb["metadata"]["labels"]["topology.kubernetes.io/zone"] = "zone-b"
        client.create("nodes", na)
        client.create("nodes", nb)
        client.create("queues", {"metadata": {"name": "default"}, "spec": {"weight": 1}})
        client.create("storageclasses", {"metadata": {"name": "standard"},
                                         "provisioner": "kat.io/fake"})
        client.create("persistentvolumes", {
            "metadata": {"name": "pv-b",
                         "labels": {"topology.kubernetes.io/zone": "zone-b"}},
            "spec": {"capacity": {"storage": "10Gi"}},
        })
        client.create("persistentvolumeclaims", {
            "metadata": {"namespace": "default", "name": "claim-b"},
            "spec": {"volumeName": "pv-b", "storageClassName": "standard"},
        })
        client.create("podgroups", make_podgroup("pg1", min_member=1))
        pod = make_pod("p0", group="pg1")
        pod["spec"]["volumes"] = [
            {"name": "data", "persistentVolumeClaim": {"claimName": "claim-b"}}
        ]
        client.create("pods", pod)

        live = LiveCache(client)
        sched = Scheduler(live, config=load_conf(FULL_CONF))
        result = sched.run_once()
        assert len(result.binds) == 1
        assert api.get("pods", "default", "p0")["spec"]["nodeName"] == "nb"
        # the model carries the resolved zone pin
        task = next(iter(live.cluster.jobs["default/pg1"].tasks.values()))
        assert task.volume_zone == "zone-b"
    finally:
        server.shutdown()


def test_attach_limit_rejects_cpu_feasible_node_over_http():
    """The attach-count axis: a node with one attach slot already consumed
    by a running PVC pod rejects a second volume pod despite having the
    cpu for it; the pod lands on the other node."""
    api = FakeApiServer()
    server, _, url = serve_api(api)
    try:
        client = HttpApiClient(url)
        n0 = make_node("n0")
        n0["status"]["allocatable"]["attachable-volumes-csi"] = 1
        n1 = make_node("n1")
        n1["status"]["allocatable"]["attachable-volumes-csi"] = 4
        client.create("nodes", n0)
        client.create("nodes", n1)
        client.create("queues", {"metadata": {"name": "default"}, "spec": {"weight": 1}})
        for i, claim in enumerate(("c0", "c1")):
            client.create("persistentvolumeclaims", {
                "metadata": {"namespace": "default", "name": claim},
                "spec": {"volumeName": f"pv{i}"},
            })
            client.create("persistentvolumes", {
                "metadata": {"name": f"pv{i}"},
                "spec": {"capacity": {"storage": "1Gi"}},
            })
        # a running pod on n0 holds its single attach slot
        holder = make_pod("holder", node="n0", phase="Running", cpu="1")
        holder["spec"]["volumes"] = [
            {"name": "v", "persistentVolumeClaim": {"claimName": "c0"}}
        ]
        client.create("pods", holder)
        client.create("podgroups", make_podgroup("pg1", min_member=1))
        pod = make_pod("p0", group="pg1", cpu="1")
        pod["spec"]["volumes"] = [
            {"name": "v", "persistentVolumeClaim": {"claimName": "c1"}}
        ]
        client.create("pods", pod)

        live = LiveCache(client)
        # n0 has cpu headroom (4 - 1 = 3 cores) but zero attach headroom
        assert live is not None
        sched = Scheduler(live, config=load_conf(FULL_CONF))
        result = sched.run_once()
        assert len(result.binds) == 1
        assert api.get("pods", "default", "p0")["spec"]["nodeName"] == "n1"
    finally:
        server.shutdown()


def test_late_pv_event_retranslates_pod():
    """WATCH-race tolerance: a pod ingested before its PV/PVC appears gets
    retranslated when the volume objects arrive (the informer-order gap
    the reference's volumebinder absorbs internally)."""
    api = FakeApiServer()
    server, _, url = serve_api(api)
    try:
        client = HttpApiClient(url)
        client.create("nodes", make_node("n0"))
        client.create("queues", {"metadata": {"name": "default"}, "spec": {"weight": 1}})
        client.create("podgroups", make_podgroup("pg1", min_member=1))
        pod = make_pod("p0", group="pg1")
        pod["spec"]["volumes"] = [
            {"name": "v", "persistentVolumeClaim": {"claimName": "late"}}
        ]
        client.create("pods", pod)
        live = LiveCache(client)
        live.sync()
        task = next(iter(live.cluster.jobs["default/pg1"].tasks.values()))
        assert task.volume_zone == ""  # PVC not seen yet: no zone pin
        # PV + PVC arrive later through the watch
        client.create("persistentvolumes", {
            "metadata": {"name": "pvx",
                         "labels": {"topology.kubernetes.io/zone": "z9"}},
            "spec": {},
        })
        client.create("persistentvolumeclaims", {
            "metadata": {"namespace": "default", "name": "late"},
            "spec": {"volumeName": "pvx"},
        })
        live.sync()
        task = next(iter(live.cluster.jobs["default/pg1"].tasks.values()))
        assert task.volume_zone == "z9"
    finally:
        server.shutdown()


def test_http_evict_with_stale_rv_is_409(http_api):
    """The evict compare-and-delete precondition must survive the HTTP
    crossing: a stale expectResourceVersion DELETE on a pod gets 409."""
    api, client = http_api
    client.create("pods", make_pod("p1", group="g"))
    stale_rv = client.get("pods", "default", "p1")["metadata"]["resourceVersion"]
    client.bind_pod("default", "p1", "n1")  # bumps the rv server-side
    with pytest.raises(ApiError) as ei:
        client.evict_pod("default", "p1", expect_rv=stale_rv)
    assert ei.value.status == 409
    assert client.get("pods", "default", "p1") is not None
    client.evict_pod("default", "p1")  # unconditional still works
    assert client.get("pods", "default", "p1") is None


def test_http_410_compaction_forces_relist(http_api):
    """A compacted watch window over the WIRE arrives as a plain
    ApiError(status=410), not the GoneError class — the live cache must
    still relist and converge on the store."""
    api, client = http_api
    client.create("nodes", make_node("n0"))
    for i in range(3):
        client.create("pods", make_pod(f"p{i}", group="g"))
    cache = LiveCache(client)
    cache.sync()
    # churn the cache never sees as events, then close the window
    api.bind_pod("default", "p0", "n0")
    api.delete("pods", "default", "p1")
    api.compact()
    cache.sync()  # wire 410 -> relist
    model = {
        uid: t for j in cache.cluster.jobs.values() for uid, t in j.tasks.items()
    }
    assert set(model) == {"uid-default-p0", "uid-default-p2"}
    assert model["uid-default-p0"].node_name == "n0"
