"""Cache-plane parity: PDB gangs, namespace-as-queue, bind/evict failure
resync, deferred job GC, volume binder hooks.

Reference behaviors: api/job_info.go:188-205 (SetPDB/UnsetPDB),
cache/event_handlers.go:458-492 (PDB events), :656-673 (namespace queues),
cache/cache.go:519-547 (errTasks resync), :476-517 (deferred job GC),
cache/interface.go:59-76 (Binder/Evictor/VolumeBinder seams).
"""
import pytest

from kube_arbitrator_tpu.api.types import TaskStatus
from kube_arbitrator_tpu.cache import SimCluster, build_snapshot
from kube_arbitrator_tpu.framework import Scheduler, load_conf
from kube_arbitrator_tpu.options import ServerOptions, reset_options, set_options

GB = 1024**3


@pytest.fixture(autouse=True)
def _fresh_options():
    reset_options()
    yield
    reset_options()


def _conf(actions="allocate, backfill"):
    return load_conf(
        f"""
actions: "{actions}"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
"""
    )


# ---- PDB ----


def test_pdb_defines_gang_min_available():
    sim = SimCluster()
    sim.add_queue("default")
    sim.add_node("n1", cpu_milli=4000, memory=8 * GB)
    job = sim.add_pdb("web", min_available=3)
    assert job.uid == "default/web"
    assert job.min_available == 3
    assert job.queue_uid == "default"  # default_queue is set → wins over ns
    # only 2 tasks fit the budget of this test: gang must block all of them
    for _ in range(2):
        sim.add_task(job, cpu_milli=1000, memory=1 * GB)
    sched = Scheduler(sim, config=_conf())
    sched.run_once()
    assert sim.binder.binds == {}

    # a third replica arrives → the gang becomes satisfiable and releases
    sim.add_task(job, cpu_milli=1000, memory=1 * GB)
    sched.run_once()
    assert len(sim.binder.binds) == 3


def test_pdb_queue_falls_back_to_namespace_without_default_queue():
    set_options(ServerOptions(default_queue=""))
    sim = SimCluster()
    job = sim.add_pdb("web", min_available=1, namespace="team-a")
    assert job.queue_uid == "team-a"


def test_delete_pdb_clears_gang():
    sim = SimCluster()
    sim.add_queue("default")
    sim.add_node("n1", cpu_milli=4000, memory=8 * GB)
    job = sim.add_pdb("web", min_available=5)
    for _ in range(2):
        sim.add_task(job, cpu_milli=1000, memory=1 * GB)
    sched = Scheduler(sim, config=_conf())
    sched.run_once()
    assert sim.binder.binds == {}  # gang of 5 unsatisfiable
    sim.delete_pdb("web")
    assert job.min_available == 0
    sched.run_once()
    assert len(sim.binder.binds) == 2  # no gang constraint anymore


def test_snapshot_tolerates_empty_pdb_job():
    sim = SimCluster()
    sim.add_queue("default")
    sim.add_node("n1")
    sim.add_pdb("empty", min_available=2)  # PDB exists before any pod
    snap = build_snapshot(sim.cluster)
    assert snap.tensors.num_tasks >= 0  # just must not crash


# ---- namespace-as-queue ----


def test_namespace_as_queue_resolution():
    set_options(ServerOptions(namespace_as_queue=True))
    sim = SimCluster()
    assert sim.add_namespace("team-a", weight=3) is not None
    sim.add_namespace("team-b")
    sim.add_node("n1", cpu_milli=4000, memory=8 * GB)
    job = sim.add_job("j1", namespace="team-a")  # no queue named
    assert job.queue_uid == "team-a"
    sim.add_task(job, cpu_milli=500, memory=GB)
    sched = Scheduler(sim, config=_conf())
    sched.run_once()
    assert len(sim.binder.binds) == 1


def test_add_namespace_noop_when_option_off():
    sim = SimCluster()
    assert sim.add_namespace("team-a") is None
    assert "team-a" not in sim.cluster.queues


def test_options_check():
    with pytest.raises(ValueError):
        ServerOptions(enable_leader_election=True).check()
    ServerOptions(enable_leader_election=True, lock_object_namespace="kube-system").check()


# ---- bind failure → errTasks resync ----


def test_bind_failure_diverts_to_resync_and_retries():
    sim = SimCluster()
    sim.add_queue("default")
    sim.add_node("n1", cpu_milli=4000, memory=8 * GB)
    job = sim.add_job("j1")
    t1 = sim.add_task(job, cpu_milli=500, memory=GB)
    t2 = sim.add_task(job, cpu_milli=500, memory=GB)
    sim.binder.fail_uids.add(t1.uid)

    sched = Scheduler(sim, config=_conf())
    sched.run_once()
    # t2 bound; t1's backend call failed: stays pending, queued for resync
    assert t2.uid in sim.binder.binds
    assert t1.uid not in sim.binder.binds
    assert t1.status == TaskStatus.PENDING
    assert sim.resync_queue == [t1.uid]
    assert any(e.kind == "FailedScheduling" for e in sim.events)

    # backend recovers → next cycle resyncs and retries the bind
    sim.binder.fail_uids.clear()
    sched.run_once()
    assert t1.uid in sim.binder.binds
    assert sim.resync_queue == []
    # no double-accounting on the node
    n1 = sim.cluster.nodes["n1"]
    assert len(n1.tasks) == 2


def test_evict_failure_keeps_task_running():
    sim = SimCluster()
    sim.add_queue("default")
    sim.add_node("n1", cpu_milli=1000, memory=GB)
    job = sim.add_job("j1")
    t = sim.add_task(job, cpu_milli=500, memory=GB // 2, status=TaskStatus.RUNNING, node="n1")
    sim.evictor.fail_uids.add(t.uid)
    from kube_arbitrator_tpu.cache import EvictIntent

    sim.apply_evicts([EvictIntent(task_uid=t.uid)])
    assert t.status == TaskStatus.RUNNING  # eviction never actuated
    assert sim.resync_queue == [t.uid]
    sim.process_resync()
    assert sim.resync_queue == []


# ---- volume binder ----


def test_volume_hooks_called_per_bind():
    sim = SimCluster()
    sim.add_queue("default")
    sim.add_node("n1", cpu_milli=4000, memory=8 * GB)
    job = sim.add_job("j1")
    sim.add_task(job, cpu_milli=500, memory=GB)
    sched = Scheduler(sim, config=_conf())
    sched.run_once()
    assert len(sim.volume_binder.allocated) == 1
    assert len(sim.volume_binder.bound) == 1


def test_volume_allocate_failure_is_gang_atomic():
    """A volume failure for one gang member must not bind the others."""
    sim = SimCluster()
    sim.add_queue("default")
    sim.add_node("n1", cpu_milli=4000, memory=8 * GB)
    job = sim.add_job("gang", min_available=2)
    t1 = sim.add_task(job, cpu_milli=500, memory=GB)
    t2 = sim.add_task(job, cpu_milli=500, memory=GB)
    sim.volume_binder.fail_allocate_uids.add(t1.uid)
    sched = Scheduler(sim, config=_conf())
    sched.run_once()
    assert sim.binder.binds == {}  # whole gang batch dropped
    assert t1.status == TaskStatus.PENDING and t2.status == TaskStatus.PENDING
    assert sorted(sim.resync_queue) == sorted([t1.uid, t2.uid])

    sim.volume_binder.fail_allocate_uids.clear()
    sched.run_once()
    assert len(sim.binder.binds) == 2


# ---- deferred job GC ----


def test_deferred_job_gc():
    sim = SimCluster()
    sim.add_queue("default")
    job = sim.add_job("j1")
    t = sim.add_task(job, cpu_milli=100, memory=GB)
    sim.delete_job("j1", now=100.0)

    # before the delay: kept
    assert sim.collect_garbage(now=102.0) == []
    # after the delay but task still live: kept
    assert sim.collect_garbage(now=200.0) == []
    t.status = TaskStatus.SUCCEEDED
    # terminal → collected
    assert sim.collect_garbage(now=200.0) == ["j1"]
    assert "j1" not in sim.cluster.jobs
    # FIFO drained
    assert sim.collect_garbage(now=300.0) == []


def test_decision_plane_never_mutates_model():
    """Cache-mutation-detector analog (SURVEY §5: the reference's unit
    harness sets KUBE_CACHE_MUTATION_DETECTOR=true, panicking when a
    shared informer object is mutated).  Here: snapshot build + the full
    jitted cycle + decode must leave the cluster model untouched — only
    actuation writes."""
    from kube_arbitrator_tpu.cache import build_snapshot, generate_cluster
    from kube_arbitrator_tpu.cache.decode import decode_decisions
    from kube_arbitrator_tpu.ops import schedule_cycle
    from kube_arbitrator_tpu.utils.mutation_detector import assert_no_model_mutation

    sim = generate_cluster(num_nodes=20, num_jobs=6, tasks_per_job=8,
                           num_queues=3, seed=13, running_fraction=0.4)
    with assert_no_model_mutation(sim.cluster):
        snap = build_snapshot(sim.cluster)
        dec = schedule_cycle(
            snap.tensors, actions=("reclaim", "allocate", "backfill", "preempt")
        )
        decode_decisions(snap, dec)

    # control: actuation IS a mutation the detector must catch
    import pytest
    from kube_arbitrator_tpu.utils.mutation_detector import ModelMutated

    snap = build_snapshot(sim.cluster)
    dec = schedule_cycle(snap.tensors)
    binds, evicts = decode_decisions(snap, dec)
    assert binds
    with pytest.raises(ModelMutated):
        with assert_no_model_mutation(sim.cluster):
            sim.apply_binds(binds)
