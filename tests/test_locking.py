"""Tests for the dynamic half of the concurrency sanitizer
(``kube_arbitrator_tpu.utils.locking``): the zero-overhead off path, the
witness graph (inversions, hold SLO, reentrancy), guarded-state modes,
the race-soak runner's canary postures, and the static-vs-witnessed
reconciliation artifact.
"""
import json
import threading
import time

import pytest

from kube_arbitrator_tpu.utils import locking


@pytest.fixture
def sanitized():
    """Force the shim on with a fresh witness; restore on exit so the
    rest of the suite keeps constructing plain threading locks."""
    prev = locking.force_sanitize(True)
    locking.reset_witness()
    yield locking.witness()
    locking.reset_witness()
    locking.force_sanitize(prev)


def _on_thread(fn, name="kat-test"):
    t = threading.Thread(target=fn, name=name)
    t.start()
    t.join()


# ---------------------------------------------------------------------------
# off path: zero residue


def test_off_path_returns_exact_stdlib_types():
    prev = locking.force_sanitize(False)
    try:
        assert type(locking.Lock("x")) is type(threading.Lock())
        assert type(locking.RLock("x")) is type(threading.RLock())
        assert type(locking.Condition()) is threading.Condition
        lk = threading.Lock()
        assert type(locking.Condition(lk)) is threading.Condition
    finally:
        locking.force_sanitize(prev)


def test_off_path_register_guarded_is_a_noop():
    prev = locking.force_sanitize(False)
    try:
        class Box:
            pass

        b = Box()
        b.items = {}
        out = locking.register_guarded(None, b, ("items",))
        assert out is b
        assert type(b) is Box            # class not swapped
        assert type(b.items) is dict     # container not wrapped
        assert not hasattr(b, "_kat_guards")
    finally:
        locking.force_sanitize(prev)


# ---------------------------------------------------------------------------
# witness graph


def test_witness_sees_lock_order_inversion(sanitized):
    a = locking.Lock("t.a")
    b = locking.Lock("t.b")

    def fwd():
        with a:
            with b:
                pass

    def rev():
        with b:
            with a:
                pass

    _on_thread(fwd)
    _on_thread(rev)
    assert frozenset(("t.a", "t.b")) in sanitized.inversions()
    kinds = [f["kind"] for f in sanitized.findings]
    assert "inversion" in kinds
    rep = sanitized.report()
    assert {"src": "t.a", "dst": "t.b"}.items() <= rep["edges"][0].items()


def test_expected_inversion_is_witnessed_but_not_a_finding(sanitized):
    sanitized.expect_inversion("t.a", "t.b")
    a = locking.Lock("t.a")
    b = locking.Lock("t.b")
    _on_thread(lambda: (a.acquire(), b.acquire(), b.release(), a.release()))
    _on_thread(lambda: (b.acquire(), a.acquire(), a.release(), b.release()))
    assert frozenset(("t.a", "t.b")) in sanitized.inversions()
    assert [f for f in sanitized.findings if f["kind"] == "inversion"] == []


def test_rlock_reentry_adds_no_edges(sanitized):
    outer = locking.Lock("t.outer")
    r = locking.RLock("t.re")
    with r:
        with outer:
            with r:       # reentrant: must NOT witness outer -> t.re
                pass
    assert ("t.outer", "t.re") not in sanitized.edges
    assert ("t.re", "t.outer") in sanitized.edges


def test_hold_slo_breach_is_flagged(sanitized, monkeypatch):
    monkeypatch.setenv("KAT_SANITIZE_HOLD_SLO_MS", "1")
    lk = locking.Lock("t.slow")
    with lk:
        time.sleep(0.01)
    holds = [f for f in sanitized.findings if f["kind"] == "hold_slo"]
    assert holds and holds[0]["lock"] == "t.slow"


def test_condition_wait_notify_roundtrip(sanitized):
    cond = locking.Condition(name="t.cond")
    ready = []

    def waiter():
        with cond:
            while not ready:
                cond.wait(timeout=5)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.02)
    with cond:
        ready.append(1)
        cond.notify_all()
    t.join(timeout=5)
    assert not t.is_alive()
    # the wait released and re-acquired through the shim without
    # corrupting the per-thread bookkeeping
    assert not sanitized.held_names()


# ---------------------------------------------------------------------------
# guarded state


class _Box:
    def __init__(self):
        self.count = 0
        self.items = {}
        self.rows = []
        self.tags = set()


def test_guard_lock_mode_flags_unlocked_mutation(sanitized):
    lk = locking.Lock("t.guard")
    box = locking.register_guarded(lk, _Box(), ("count", "items"), name="Box")
    with lk:
        box.count = 1          # locked: fine
        box.items["a"] = 1
    box.count = 2              # rebind without the lock
    box.items["b"] = 2         # container mutation without the lock
    guards = [f for f in sanitized.findings if f["kind"] == "guard"]
    assert {f["field"] for f in guards} == {"count", "items"}
    assert all(f["lock"] == "t.guard" and f["mode"] == "lock" for f in guards)


def test_guard_rebound_container_stays_wrapped(sanitized):
    lk = locking.Lock("t.rewrap")
    box = locking.register_guarded(lk, _Box(), ("rows",), name="Box")
    with lk:
        box.rows = []          # rebind to a fresh plain list, under lock
    box.rows.append(1)         # must still be checked
    guards = [f for f in sanitized.findings if f["kind"] == "guard"]
    assert [f["field"] for f in guards] == ["rows"]


def test_guard_single_writer_mode(sanitized):
    box = locking.register_guarded(None, _Box(), ("tags",), name="Box")
    box.tags.add("mine")                        # first mutator claims
    _on_thread(lambda: box.tags.add("theirs"))  # any other thread: finding
    guards = [f for f in sanitized.findings if f["kind"] == "guard"]
    assert len(guards) == 1
    assert guards[0]["mode"] == "single-writer"
    assert guards[0]["field"] == "tags"


# ---------------------------------------------------------------------------
# race soak: both canary postures, and the reconciliation artifact


@pytest.mark.slow
def test_race_soak_clean_under_shim(tmp_path):
    from kube_arbitrator_tpu.chaos.race_soak import run_race_soak

    rep = run_race_soak(seed=0, cycles=2, out_dir=str(tmp_path))
    assert rep.ok, rep.breaches
    assert "canary:witnessed" in rep.outcomes
    assert rep.digests == []   # schedules are nondeterministic by design
    kinds = {d["kind"] for d in rep.detections}
    assert "lock_inversion_canary" in kinds
    arts = sorted(tmp_path.glob("sanitizer-*.json"))
    assert arts, "no reconciliation artifact written"
    payload = json.loads(arts[0].read_text())
    assert payload["format_version"] == 1
    assert payload["static"]["locks"]
    # the canary is statically invisible by construction
    assert "canary.a" not in payload["static"]["locks"]


@pytest.mark.slow
def test_race_soak_blind_canary_breaches():
    from kube_arbitrator_tpu.chaos.race_soak import run_race_soak

    rep = run_race_soak(seed=0, cycles=1, disabled=("sanitizer",))
    assert not rep.ok
    assert [b.invariant for b in rep.breaches] == ["sanitizer_witness"]
    assert "canary:unwitnessed" in rep.outcomes


def test_reconcile_flags_unmodeled_and_unwitnessed_edges():
    from kube_arbitrator_tpu.analysis.rules.lockorder import LockGraph
    from kube_arbitrator_tpu.analysis.sanitizer import reconcile

    graph = LockGraph()
    graph.add_site("x.a", "m.py", 1)
    graph.add_edge("x.a", "x.b", "m.py", 2)      # static only
    report = {"edges": [
        {"src": "x.c", "dst": "x.d", "count": 1, "stack": ""},   # dynamic only
        {"src": "canary.a", "dst": "canary.b", "count": 1, "stack": ""},
        {"src": "anon-lock-1", "dst": "x.a", "count": 1, "stack": ""},
    ]}
    mm = reconcile(graph, report)
    assert mm["unmodeled"] == [["x.c", "x.d"]]    # canary/anon ignored
    assert mm["unwitnessed"] == [["x.a", "x.b"]]


def test_dump_artifact_sequences_files(tmp_path):
    from kube_arbitrator_tpu.analysis.rules.lockorder import LockGraph
    from kube_arbitrator_tpu.analysis.sanitizer import dump_artifact

    graph = LockGraph()
    graph.add_site("x.a", "m.py", 1)
    p1 = dump_artifact(str(tmp_path), graph, {"edges": []})
    p2 = dump_artifact(str(tmp_path), graph, {"edges": []})
    assert p1.endswith("sanitizer-0001.json")
    assert p2.endswith("sanitizer-0002.json")
    assert json.loads((tmp_path / "sanitizer-0001.json").read_text())["mismatches"]
