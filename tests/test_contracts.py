"""Contract-pass self-tests: the eval_shape harness must (a) pass the
real tree and (b) fail LOUDLY and PRECISELY on a seeded schema mutation —
a checker that can silently go green is worse than none.
"""
import pathlib
import subprocess
import sys

import numpy as np
import pytest

from kube_arbitrator_tpu.analysis import contracts

REPO = pathlib.Path(__file__).resolve().parents[1]


def kernels_named(findings):
    return sorted({
        f.message.split("`")[1]
        for f in findings
        if f.message.startswith("kernel ")
    })


def test_real_tree_contracts_are_clean():
    findings = contracts.check_contracts()
    assert findings == [], "\n".join(f.format() for f in findings)


def test_schema_matches_snapshot_dataclass_exactly():
    assert contracts.check_schema_fields() == []


# ---------------------------------------------------------------------------
# seeded violations: mutate ONE schema dtype, expect EXACTLY the affected
# stage reported


def test_mutated_snapshot_dtype_reports_exactly_the_consuming_kernel():
    # rv_block_start is reclaim's canon-pack window index; as float32 the
    # dynamic-slice start inside the reclaim kernel is no longer integral
    seeded = contracts.mutated(
        contracts.SNAPSHOT_SCHEMA, "rv_block_start", "float32"
    )
    findings = contracts.check_kernels(seeded)
    assert findings, "seeded dtype violation went undetected"
    assert {f.rule for f in findings} == {"KAT-CTR-004"}
    # BOTH reclaim flavors consume the canon pack — the optimistic
    # engine is a registered kernel and must be caught too
    assert kernels_named(findings) == ["reclaim", "reclaim_optimistic"]
    assert all("rv_block_start" in f.message or "reclaim" in f.message for f in findings)


def test_mutated_producer_dtype_reports_exactly_that_field():
    # declare task_resreq as float64: the real producer (correctly)
    # emits float32, so the producer check must flag exactly this field —
    # the direction the np.float64 DEVICE_SCALE bug class travels
    seeded = contracts.mutated(contracts.SNAPSHOT_SCHEMA, "task_resreq", "float64")
    findings = contracts.check_producer(seeded)
    assert len(findings) == 1
    assert findings[0].rule == "KAT-CTR-002"
    assert "task_resreq" in findings[0].message


def test_mutated_state_dtype_is_caught_at_the_stage_boundary():
    # group_placed drifting to float32 must be caught for every kernel
    # that threads state (the stage n -> n+1 seam), not silently washed
    seeded = contracts.mutated(contracts.STATE_SCHEMA, "group_placed", "float32")
    findings = contracts.check_kernels(state_schema=seeded)
    assert findings
    assert {"KAT-CTR-003"} <= {f.rule for f in findings}


def test_snapshot_build_asserts_pack_dtypes():
    # the producer-side runtime guard (cache/snapshot.py) enforces the
    # same schema at pack build time: a float64 field that slipped past
    # the explicit crossover cast must refuse to leave the producer
    import dataclasses

    from kube_arbitrator_tpu.cache import snapshot as snapmod
    from kube_arbitrator_tpu.cache.sim import SimCluster

    sim = SimCluster()
    sim.add_queue("default")
    sim.add_node("n1", cpu_milli=1000, memory=1024**3)
    j = sim.add_job("j1", queue="default")
    sim.add_task(j, 100, 1024**2)
    snap = snapmod.build_snapshot(sim.cluster)  # clean build passes the guard
    leaked = dataclasses.replace(
        snap.tensors,
        task_resreq=np.asarray(snap.tensors.task_resreq, dtype=np.float64),
    )
    with pytest.raises(TypeError, match="dtype contract"):
        snapmod._assert_pack_dtypes(leaked)
    assert snapmod.to_device_units(np.zeros(4)).dtype == snapmod.DEVICE_DTYPE


def test_mutated_arena_producer_dtype_reports_exactly_that_field():
    # the arena's delta path is a SECOND snapshot producer: declaring
    # group_size as int64 must make the arena check flag exactly that
    # field (the real delta path correctly emits int32)
    seeded = contracts.mutated(contracts.SNAPSHOT_SCHEMA, "group_size", "int64")
    findings = contracts.check_arena_producer(seeded)
    assert len(findings) == 1
    assert findings[0].rule == "KAT-CTR-007"
    assert "group_size" in findings[0].message
    assert "delta path" in findings[0].message or "SnapshotArena" in findings[0].message


def test_arena_producer_clean_on_real_tree():
    assert contracts.check_arena_producer() == []


def test_batched_turns_clean_on_real_tree():
    assert contracts.check_batched_turns() == []


def test_mutated_turn_schema_reports_exactly_that_field():
    # KAT-CTR-008: declare the batched selection's budget column as
    # float32 — the real select_turns (correctly) returns int32, and the
    # slot loops of BOTH evictive paths index by it, so the analyzer must
    # flag exactly this field for both budget modes
    seeded = contracts.mutated(contracts.TURN_SCHEMA, "budget", "float32")
    findings = contracts.check_batched_turns(turn_schema=seeded)
    assert len(findings) == 2  # one per budget mode (allocate, preempt)
    assert {f.rule for f in findings} == {"KAT-CTR-008"}
    assert all("`budget`" in f.message for f in findings)


def test_reclaim_turns_clean_on_real_tree():
    assert contracts.check_reclaim_turns() == []


def test_mutated_reclaim_turn_schema_reports_exactly_that_field():
    # KAT-CTR-009: declare the batched reclaim selection's pop column as
    # float32 — the real reclaim_select_turns (correctly) returns bool,
    # and _reclaim_canon_batched's thin tail gathers it per turn, so the
    # analyzer must flag exactly this field
    seeded = contracts.mutated(contracts.RECLAIM_TURN_SCHEMA, "pop", "float32")
    findings = contracts.check_reclaim_turns(turn_schema=seeded)
    assert len(findings) == 1
    assert findings[0].rule == "KAT-CTR-009"
    assert "`pop`" in findings[0].message


def test_audit_aux_clean_on_real_tree():
    assert contracts.check_audit_aux() == []


def test_decode_lists_pass_is_clean_and_axes_track_caps():
    # KAT-CTR-011 green on the real commit tail, with the B/E axes
    # resolved live from the caps formula (drift between decode_caps and
    # the schema would fail here first)
    assert contracts.check_decode_lists() == []
    from kube_arbitrator_tpu.ops.cycle import decode_caps

    axes = contracts.decode_axes(contracts.DEFAULT_AXES)
    assert (axes["B"], axes["E"]) == decode_caps(contracts.DEFAULT_AXES["T"])


def test_mutated_decode_lists_schema_reports_exactly_that_field():
    # KAT-CTR-011: declare bind_idx as float32 — the real commit tail
    # (correctly) emits int32 ordinals, and cache/decode.py gathers them
    # host-side into the actuated bind stream, so the analyzer must flag
    # exactly this field
    seeded = contracts.mutated(
        contracts.DECODE_LISTS_SCHEMA, "bind_idx", "float32"
    )
    findings = contracts.check_decode_lists(lists_schema=seeded)
    assert len(findings) == 1
    assert findings[0].rule == "KAT-CTR-011"
    assert "`bind_idx`" in findings[0].message


def test_mutated_audit_aux_schema_reports_exactly_that_field():
    # KAT-CTR-010: declare the audit attribution's evict_round as float32
    # — the real commit_cycle (correctly) passes int32 through from
    # AllocState, and utils/audit.py decodes it host-side (and it crosses
    # the RPC reply pack), so the analyzer must flag exactly this field
    seeded = contracts.mutated(
        contracts.AUDIT_AUX_SCHEMA, "evict_round", "float32"
    )
    findings = contracts.check_audit_aux(audit_schema=seeded)
    assert len(findings) == 1
    assert findings[0].rule == "KAT-CTR-010"
    assert "`evict_round`" in findings[0].message


def test_wire_names_clean_on_real_tree():
    # KAT-CTR-013: every CycleDecisions field has a same-named consumer
    # on the reply-pack path and every literal consumer read names a
    # real field (the scan itself is exercised: it must see reads for
    # all 19 fields, not return an empty map and vacuously pass)
    assert contracts.check_wire_names() == []
    reads = contracts._scan_wire_reads()
    import dataclasses as dc

    from kube_arbitrator_tpu.ops.cycle import CycleDecisions

    for f in dc.fields(CycleDecisions):
        assert f.name in reads, f"no by-name consumer read for {f.name}"


def test_wire_names_producer_rename_reports_only_ctr013():
    # seed a producer-side rename: evict_round -> evict_rnd.  The schema
    # mismatch (both directions) and the missing consumer must all
    # surface, and only as KAT-CTR-013
    import dataclasses as dc

    from kube_arbitrator_tpu.ops.cycle import CycleDecisions

    names = tuple(
        "evict_rnd" if f.name == "evict_round" else f.name
        for f in dc.fields(CycleDecisions)
    )
    findings = contracts.check_wire_names(field_names=names)
    assert findings and {f.rule for f in findings} == {"KAT-CTR-013"}
    text = "\n".join(f.message for f in findings)
    assert "`evict_rnd`" in text and "`evict_round`" in text


def test_wire_names_consumer_rename_reports_only_ctr013():
    # seed a consumer-side drift: the audit plane stops reading
    # evict_round (renamed on its end) and instead reads a ghost field
    reads = contracts._scan_wire_reads()
    seeded = dict(reads)
    # audit.py no longer reads evict_round (another module still does),
    # and reads a ghost name instead
    seeded["evict_round"] = {"framework/session.py": 1}
    seeded["evict_rnd"] = {"utils/audit.py": 1}
    findings = contracts.check_wire_names(consumer_reads=seeded)
    assert findings and {f.rule for f in findings} == {"KAT-CTR-013"}
    text = "\n".join(f.message for f in findings)
    # the plane going blind AND the ghost read both surface
    assert "utils/audit.py" in text and "`evict_rnd`" in text


def test_producer_crash_becomes_a_finding_not_a_traceback(monkeypatch):
    # a build_snapshot that RAISES (e.g. its own pack-dtype guard firing)
    # must surface as a KAT-CTR-002 finding, not crash the analyzer and
    # drop every other finding of the run
    from kube_arbitrator_tpu.cache import snapshot as snapmod

    def boom(cluster):
        raise TypeError("snapshot pack dtype contract violation: seeded")

    monkeypatch.setattr(snapmod, "build_snapshot", boom)
    findings = contracts.check_producer()
    assert len(findings) == 1
    assert findings[0].rule == "KAT-CTR-002"
    assert "seeded" in findings[0].message


def test_snapshot_struct_honors_schema_and_axes():
    st = contracts.snapshot_struct()
    assert st.task_resreq.shape == (
        contracts.DEFAULT_AXES["T"], contracts.DEFAULT_AXES["R"]
    )
    assert st.task_resreq.dtype == np.float32
    assert st.rv_block_start.shape == (contracts.DEFAULT_AXES["N"] + 1,)
    assert st.rv_window == contracts.SNAPSHOT_STATIC["rv_window"]


# ---------------------------------------------------------------------------
# CLI integration: the contract pass rides the default gate


@pytest.mark.slow
def test_cli_runs_contract_pass_on_package_scope(tmp_path):
    import json

    def run():
        r = subprocess.run(
            [
                sys.executable, "-m", "kube_arbitrator_tpu.analysis",
                "--format", "json",
                "--cache-dir", str(tmp_path / "kat-cache"),  # isolated cache
                # the COMMITTED baseline is part of the gate: it holds
                # exactly the justified KAT-EFF allocation floors, and
                # anything beyond it must fail this test
                "--baseline", str(REPO / ".kat-baseline.json"),
                str(REPO / "kube_arbitrator_tpu"), str(REPO / "tests"),
            ],
            cwd=REPO,
            capture_output=True, text=True, timeout=600,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        return json.loads(r.stdout)

    cold = run()
    assert cold["findings"] == []
    warm = run()
    assert warm["findings"] == []
    # the <10s budget is the CACHED steady state (deploy/check.sh runs
    # this every push); the cold run pays one eval_shape of the pipeline
    assert warm["wall_time_s"] < 10.0, "full-tree gate must stay under 10s warm"
    assert warm["wall_time_s"] < cold["wall_time_s"]
