"""Live-plane soak: sustained churn through LiveCache + the HTTP shim.

The informer-cache analog of the reference's e2e cluster runs
(test/e2e/util.go drives namespaces/jobs/taints against a real 3-node
DinD cluster and polls for convergence): ~5k pods of cumulative churn
(a 2k-pod live set, with whole gangs evicted+deleted and respawned and
node cordon flaps, every cycle) pumped through the watch plane for 50
scheduler cycles, asserting at the end that the in-memory model and the
apiserver agree exactly (no snapshot drift) and that node accounting
closes.

Scale headroom (round-5 one-off, not in the suite): the same harness at
4x — 160 nodes, a 4k-pod live set, 12 jobs churned per cycle for 60
cycles (~22k pods through the plane) — passed every assertion in 163 s
with 1.7 GB RSS, no recompiles and no drift; the suite keeps the 1x
configuration for wall-clock budget.

Wall-clock note: churn replaces jobs with SAME-SIZE jobs and the
snapshot's sticky geometric shape buckets (snapshot._bucket) absorb the
remaining count drift, so steady-state cycles run ~0.4 s with no
recompiles; the 50-cycle phase measures 42 s with a warm XLA cache (the
conftest persistent cache), ~144 s cold.  The unequal queue weights keep
a steady reclaim/controller-recreate current (~39 evictions/cycle)
flowing through the watch plane, like the reference's e2e reclaim
scenario (test/e2e/queue.go).
"""
import random
import time

import numpy as np
import pytest

from kube_arbitrator_tpu.api import TaskStatus
from kube_arbitrator_tpu.cache import FakeApiServer, LiveCache
from kube_arbitrator_tpu.cache.httpapi import HttpApiClient, serve_api
from kube_arbitrator_tpu.framework import Scheduler
from kube_arbitrator_tpu.framework.conf import load_conf
from kube_arbitrator_tpu.options import reset_options

from test_live_cache import make_node, make_pod, make_podgroup

FULL_CONF = (
    'actions: "reclaim, allocate, backfill, preempt"\n'
    "tiers:\n"
    "- plugins:\n"
    "  - name: priority\n"
    "  - name: gang\n"
    "- plugins:\n"
    "  - name: drf\n"
    "  - name: predicates\n"
    "  - name: proportion\n"
)

N_NODES = 40
N_QUEUES = 4
PODS_PER_JOB = 25
N_JOBS = 40           # 1,000-pod live set
N_CYCLES = 50
CHURN_JOBS = 3        # jobs replaced per cycle -> 3*25*50 = 3.75k churned
                      # pods + the 1k seed = ~5k pods through the plane


@pytest.fixture(autouse=True)
def _fresh_options():
    reset_options()
    yield
    reset_options()


def _assert_converged(api: FakeApiServer, live: LiveCache) -> int:
    """Model == apiserver, field by field; returns the live pod count."""
    pods, _ = api.list("pods")
    api_by_uid = {p["metadata"]["uid"]: p for p in pods}
    model_tasks = {
        t.uid: t for job in live.cluster.jobs.values() for t in job.tasks.values()
    }
    ours = {
        uid: p for uid, p in api_by_uid.items()
        if p["spec"].get("schedulerName") == "kube-batch"
    }
    assert set(ours) == set(model_tasks), (
        f"model/apiserver divergence: only-api={len(set(ours) - set(model_tasks))} "
        f"only-model={len(set(model_tasks) - set(ours))}"
    )
    for uid, pod in ours.items():
        t = model_tasks[uid]
        assert pod["spec"].get("nodeName", "") == t.node_name, (
            uid, pod["spec"].get("nodeName"), t.node_name)
    # node accounting closes: per node, the model's used == the resreq sum
    # of the assigned non-terminal tasks it hosts
    for name, node in live.cluster.nodes.items():
        expect = np.zeros_like(np.asarray(node.used))
        for t in model_tasks.values():
            if t.node_name == name and int(t.status) in (
                int(TaskStatus.BOUND), int(TaskStatus.RUNNING),
                int(TaskStatus.RELEASING), int(TaskStatus.BINDING),
            ):
                expect = expect + np.asarray(t.resreq)
        assert np.allclose(np.asarray(node.used), expect, atol=1e-6), (
            f"node {name} accounting drift")
    return len(model_tasks)


def test_arena_soak_50_cycles_matches_full_rebuild():
    """Arena acceptance soak: >=50 cycles through Scheduler.run with the
    incremental snapshot plane on, against a twin scheduler rebuilding
    from scratch every cycle — bind/evict decisions must match cycle for
    cycle.  Churn between cycles exercises both the delta path (binds,
    evicts, resync repairs) and the structural fallbacks (gang arrivals,
    job deletion + GC, cordon flaps), and verify_every=10 interleaves the
    byte-identity epoch check five times across the run."""
    from kube_arbitrator_tpu.cache.sim import generate_cluster

    def mk():
        return generate_cluster(num_nodes=24, num_jobs=10, tasks_per_job=8,
                                num_queues=3, seed=29, running_fraction=0.3)

    arena_sched = Scheduler(mk(), config=load_conf(FULL_CONF), arena=True)
    arena_sched.arena.verify_every = 10
    full_sched = Scheduler(mk(), config=load_conf(FULL_CONF))

    def churn(sched, cycle):
        """Deterministic mutation stream, identical for both backends."""
        sim, r = sched.sim, random.Random(1000 + cycle)
        if cycle % 7 == 3:
            j = sim.add_job(f"soak-job-{cycle}",
                            queue=f"queue-{r.randrange(3):03d}",
                            min_available=2)
            for _ in range(4):
                sim.add_task(j, 500, 512 * 1024**2)
        if cycle % 11 == 5:
            victims = sorted(
                j.uid for j in sim.cluster.jobs.values()
                if j.uid.startswith("soak-job-")
                and all(t.status == TaskStatus.PENDING for t in j.tasks.values())
            )
            if victims:
                # GC only collects jobs whose tasks are all terminal:
                # finish the tasks first (emitting the status flips),
                # so the job_removed structural path actually fires
                job = sim.cluster.jobs[victims[0]]
                for t in job.tasks.values():
                    t.status = TaskStatus.SUCCEEDED
                    if getattr(sim, "delta_sink", None) is not None:
                        sim.delta_sink.task_dirty(t.uid)
                sim.delete_job(victims[0], now=0.0)
                collected = sim.collect_garbage(now=10.0)
                assert victims[0] in collected
        if cycle % 5 == 2:
            n = list(sim.cluster.nodes.values())[r.randrange(24)]
            n.unschedulable = not n.unschedulable
            if getattr(sim, "delta_sink", None) is not None:
                sim.delta_sink.node_dirty(n.name)

    rebuild_reasons = []
    for cycle in range(50):
        churn(arena_sched, cycle)
        churn(full_sched, cycle)
        ra = arena_sched.run_once()
        rb = full_sched.run_once()
        rebuild_reasons.append(arena_sched.arena.last_rebuild_reason)
        assert sorted((b.task_uid, b.node_name) for b in ra.binds) == \
            sorted((b.task_uid, b.node_name) for b in rb.binds), cycle
        assert sorted(e.task_uid for e in ra.evicts) == \
            sorted(e.task_uid for e in rb.evicts), cycle
    assert len(arena_sched.history) == 50
    # the delta path must carry the steady-state majority — a rebuild
    # every cycle would be a degenerate (correct but pointless) arena
    delta_cycles = sum(1 for r in rebuild_reasons if r is None)
    assert delta_cycles >= 30, rebuild_reasons


def test_live_plane_soak_50_cycles():
    rng = random.Random(17)
    api = FakeApiServer()
    server, _, url = serve_api(api, token="soak-token")
    try:
        client = HttpApiClient(url, token="soak-token")
        for i in range(N_NODES):
            client.create("nodes", make_node(f"n{i}", cpu="64", memory="128Gi"))
        for q in range(N_QUEUES):
            client.create("queues", {"metadata": {"name": f"q{q}"},
                                     "spec": {"weight": 1 + q % 2}})
        live = LiveCache(client)
        sched = Scheduler(live, config=load_conf(FULL_CONF))

        next_job = 0

        def spawn_job():
            nonlocal next_job
            name = f"job{next_job}"
            next_job += 1
            client.create("podgroups", make_podgroup(
                name, min_member=4, queue=f"q{next_job % N_QUEUES}"))
            for i in range(PODS_PER_JOB):
                client.create("pods", make_pod(
                    f"{name}-p{i}", group=name, cpu="500m", memory="256Mi"))
            return name

        def kill_job(name):
            from kube_arbitrator_tpu.cache.fakeapi import ApiError

            # pod names are deterministic; evict by name (a full 2k-pod
            # LIST per kill dominated the soak's wall-clock otherwise)
            for i in range(PODS_PER_JOB):
                try:
                    client.evict_pod("default", f"{name}-p{i}")
                except ApiError as err:
                    if err.status != 404:  # already evicted by the scheduler
                        raise
            client.delete("podgroups", "default", name)

        jobs = [spawn_job() for _ in range(N_JOBS)]

        def controller_pass():
            """Job-controller emulation: recreate pods the scheduler's own
            reclaim/preempt evictions deleted (bare pods have no owner in
            this harness; a real cluster's Job controller re-creates them,
            which is also what keeps the e2e reclaim scenarios of
            test/e2e/queue.go converging).  Missing pods are detected from
            the synced model (a full LIST per cycle dominated wall-clock);
            a deletion the model has not drained yet is recreated next
            cycle, like a real controller's informer lag."""
            from kube_arbitrator_tpu.cache.fakeapi import ApiError

            live_names = {
                t.name for job in live.cluster.jobs.values()
                for t in job.tasks.values()
            }
            for name in jobs:
                for i in range(PODS_PER_JOB):
                    pod_name = f"{name}-p{i}"
                    if pod_name not in live_names:
                        try:
                            client.create("pods", make_pod(
                                pod_name, group=name, cpu="500m",
                                memory="256Mi"))
                        except ApiError as err:
                            if err.status != 409:  # exists: model lag
                                raise

        # settle: drain the seed backlog (and pay the jit warm-up) before
        # the churn phase whose wall-clock the test budgets — mirrors the
        # reference e2e's waitTasksReady gate before each scenario
        for _ in range(3):
            sched.run_once()

        t0 = time.perf_counter()
        cycle_times = []
        cordoned = None
        for cycle in range(N_CYCLES):
            # churn: replace CHURN_JOBS gangs with same-size fresh ones
            # (shape-neutral, see module docstring) + a cordon flap
            for _ in range(CHURN_JOBS):
                kill_job(jobs.pop(rng.randrange(len(jobs))))
                jobs.append(spawn_job())
            controller_pass()
            if cycle % 5 == 2:
                name = f"n{rng.randrange(N_NODES)}"
                node = api.get("nodes", "", name)
                node["spec"]["unschedulable"] = True
                client.update("nodes", node)
                cordoned = name
            elif cordoned is not None:
                node = api.get("nodes", "", cordoned)
                node["spec"]["unschedulable"] = False
                client.update("nodes", node)
                cordoned = None
            cycle_t0 = time.perf_counter()
            sched.run_once()
            cycle_times.append(time.perf_counter() - cycle_t0)
        soak_s = time.perf_counter() - t0

        # final settle: drain remaining watch events, then compare
        live.sync()
        n_live = _assert_converged(api, live)
        assert n_live >= N_JOBS * PODS_PER_JOB * 0.9, n_live
        placed = sum(
            1 for job in live.cluster.jobs.values()
            for t in job.tasks.values() if t.node_name
        )
        assert placed > n_live * 0.6, (placed, n_live)
        # the soak itself (post-seed) must hold the cadence budget
        print(f"soak churn phase: {soak_s:.1f}s")
        # Two budgets, so a slow/loaded CI host cannot fake the regression
        # this guards: the MEDIAN cycle catches a shape-stability break
        # (recompile-per-cycle turns ~0.4 s steady cycles into ~15 s ones;
        # a loaded host merely scales everything a few x), and a generous
        # total bound catches runaway growth.  Cold compile cache measured
        # 144 s total; warm (conftest persistent XLA cache) 42 s.
        med = sorted(cycle_times)[len(cycle_times) // 2]
        assert med < 5.0, f"median churn cycle {med:.2f}s — recompiling every cycle?"
        assert soak_s < 400.0, f"soak took {soak_s:.1f}s"
    finally:
        server.shutdown()
