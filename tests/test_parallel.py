"""Sharded-cycle tests on the 8-device virtual CPU mesh."""
import jax
import numpy as np
import pytest

from kube_arbitrator_tpu.cache import SimCluster, build_snapshot, generate_cluster
from kube_arbitrator_tpu.cache.decode import decode_decisions
from kube_arbitrator_tpu.ops import schedule_cycle
from kube_arbitrator_tpu.parallel import make_mesh, shard_snapshot


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest should force 8 virtual devices"
    return make_mesh()


def test_sharded_cycle_matches_unsharded(mesh):
    sim = generate_cluster(num_nodes=64, num_jobs=12, tasks_per_job=8, num_queues=3, seed=3)
    snap = build_snapshot(sim.cluster)
    dec_ref = schedule_cycle(snap.tensors)
    st_sharded = shard_snapshot(snap.tensors, mesh)
    with mesh:
        dec_sh = schedule_cycle(st_sharded)
    np.testing.assert_array_equal(np.asarray(dec_ref.task_node), np.asarray(dec_sh.task_node))
    np.testing.assert_array_equal(np.asarray(dec_ref.bind_mask), np.asarray(dec_sh.bind_mask))


def test_sharded_inputs_are_actually_distributed(mesh):
    sim = SimCluster()
    sim.add_queue("q")
    for i in range(256):
        sim.add_node(f"n{i:04d}")
    snap = build_snapshot(sim.cluster)
    st = shard_snapshot(snap.tensors, mesh)
    shards = st.node_idle.addressable_shards
    assert len(shards) == 8
    assert shards[0].data.shape[0] == 256 // 8


@pytest.mark.parametrize("ndev", [3, 5, 6])
def test_mesh_accepts_any_device_count(ndev):
    """Advisor round-2 finding: make_mesh rejected counts not dividing the
    128-node bucket, contradicting the every-slice-size claim.  Any count
    must work: shard_snapshot re-pads the node axis with invalid nodes and
    the sharded cycle still matches the unsharded one."""
    sub = make_mesh(jax.devices()[:ndev])
    sim = generate_cluster(num_nodes=50, num_jobs=8, tasks_per_job=6, num_queues=2, seed=7)
    snap = build_snapshot(sim.cluster)
    dec_ref = schedule_cycle(snap.tensors)
    st = shard_snapshot(snap.tensors, sub)
    assert st.node_idle.shape[0] % ndev == 0
    with sub:
        dec_sh = schedule_cycle(st)
    T = snap.tensors.num_tasks
    np.testing.assert_array_equal(
        np.asarray(dec_ref.task_node), np.asarray(dec_sh.task_node)[:T]
    )
    np.testing.assert_array_equal(
        np.asarray(dec_ref.bind_mask), np.asarray(dec_sh.bind_mask)[:T]
    )
