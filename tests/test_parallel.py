"""Sharded-cycle tests on the 8-device virtual CPU mesh."""
import jax
import numpy as np
import pytest

from kube_arbitrator_tpu.cache import SimCluster, build_snapshot, generate_cluster
from kube_arbitrator_tpu.cache.decode import decode_decisions
from kube_arbitrator_tpu.ops import schedule_cycle
from kube_arbitrator_tpu.parallel import make_mesh, shard_snapshot


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest should force 8 virtual devices"
    return make_mesh()


def test_sharded_cycle_matches_unsharded(mesh):
    sim = generate_cluster(num_nodes=64, num_jobs=12, tasks_per_job=8, num_queues=3, seed=3)
    snap = build_snapshot(sim.cluster)
    dec_ref = schedule_cycle(snap.tensors)
    st_sharded = shard_snapshot(snap.tensors, mesh)
    with mesh:
        dec_sh = schedule_cycle(st_sharded)
    np.testing.assert_array_equal(np.asarray(dec_ref.task_node), np.asarray(dec_sh.task_node))
    np.testing.assert_array_equal(np.asarray(dec_ref.bind_mask), np.asarray(dec_sh.bind_mask))


def test_sharded_inputs_are_actually_distributed(mesh):
    sim = SimCluster()
    sim.add_queue("q")
    for i in range(256):
        sim.add_node(f"n{i:04d}")
    snap = build_snapshot(sim.cluster)
    st = shard_snapshot(snap.tensors, mesh)
    shards = st.node_idle.addressable_shards
    assert len(shards) == 8
    assert shards[0].data.shape[0] == 256 // 8


def test_pad_nodes_fill_values_and_block_extents():
    """The re-pad path's semantics: filler nodes are invalid, node_dom
    pads with -1 (no domain), and rv_block_start extends with EMPTY
    blocks (edge-repeat) so the reclaim canon engine stays legal on a
    re-padded pack instead of silently falling to the sorted-space
    kernel."""
    from kube_arbitrator_tpu.parallel import pad_nodes

    sim = generate_cluster(
        num_nodes=50, num_jobs=8, tasks_per_job=6, num_queues=2, seed=7,
        running_fraction=0.5,
    )
    st = build_snapshot(sim.cluster).tensors
    n = st.node_idle.shape[0]
    padded = pad_nodes(st, 3)
    n2 = padded.node_idle.shape[0]
    assert n2 % 3 == 0 and n2 > n
    assert not np.asarray(padded.node_valid)[n:].any()
    assert (np.asarray(padded.node_idle)[n:] == 0).all()
    assert np.asarray(padded.rv_block_start).shape == (n2 + 1,)
    bs = np.asarray(padded.rv_block_start)
    # padding nodes own empty canon blocks: extents repeat the last value
    assert (bs[n:] == bs[n]).all()
    # real prefix untouched
    np.testing.assert_array_equal(bs[: n + 1], np.asarray(st.rv_block_start))
    if padded.node_dom.shape[0]:
        assert (np.asarray(padded.node_dom)[:, n:] == -1).all()


def test_shard_snapshot_field_specs_complete():
    """Every SnapshotTensors field whose DECLARED shape carries the node
    axis must be named in the mesh partition tables — today a new
    snapshot field silently lands replicated; this (and the KAT-CTR-012
    contract pass) makes that a hard failure at review time."""
    from kube_arbitrator_tpu.analysis.contracts import (
        SHARD_REPLICATED_OK,
        SNAPSHOT_SCHEMA,
        check_shard_layout,
    )
    from kube_arbitrator_tpu.parallel.mesh import (
        _NODE_AXIS1_FIELDS,
        _NODE_SHARDED_FIELDS,
    )

    for name, (shape, _dtype) in SNAPSHOT_SCHEMA.items():
        if name in SHARD_REPLICATED_OK:
            continue
        if shape and shape[0] == "N":
            assert name in _NODE_SHARDED_FIELDS, (
                f"{name} has leading node axis but no partition spec"
            )
        if len(shape) > 1 and shape[1] == "N":
            assert name in _NODE_AXIS1_FIELDS, (
                f"{name} has second-axis node axis but no partition spec"
            )
    # the live pass agrees (KAT-CTR-012 green on the real tables)
    assert check_shard_layout() == []


def test_shard_layout_contract_reports_seeded_drift():
    """KAT-CTR-012 teeth: a schema with one NEW node-axis field that the
    mesh tables don't know must be reported — the checker cannot go
    green silently."""
    from kube_arbitrator_tpu.analysis.contracts import (
        SNAPSHOT_SCHEMA,
        check_shard_layout,
    )

    seeded = dict(SNAPSHOT_SCHEMA)
    seeded["node_new_plane"] = (("N", "R"), "float32")
    findings = check_shard_layout(seeded)
    assert len(findings) == 1
    assert "node_new_plane" in findings[0].message
    assert findings[0].rule == "KAT-CTR-012"
    # axis mismatch direction too: declared-but-wrong-axis
    seeded2 = dict(SNAPSHOT_SCHEMA)
    seeded2["node_idle"] = (("T", "R"), "float32")
    f2 = check_shard_layout(seeded2)
    assert any("node_idle" in f.message for f in f2)


@pytest.mark.parametrize("ndev", [3, 5, 6])
def test_mesh_accepts_any_device_count(ndev):
    """Advisor round-2 finding: make_mesh rejected counts not dividing the
    128-node bucket, contradicting the every-slice-size claim.  Any count
    must work: shard_snapshot re-pads the node axis with invalid nodes and
    the sharded cycle still matches the unsharded one."""
    sub = make_mesh(jax.devices()[:ndev])
    sim = generate_cluster(num_nodes=50, num_jobs=8, tasks_per_job=6, num_queues=2, seed=7)
    snap = build_snapshot(sim.cluster)
    dec_ref = schedule_cycle(snap.tensors)
    st = shard_snapshot(snap.tensors, sub)
    assert st.node_idle.shape[0] % ndev == 0
    with sub:
        dec_sh = schedule_cycle(st)
    T = snap.tensors.num_tasks
    np.testing.assert_array_equal(
        np.asarray(dec_ref.task_node), np.asarray(dec_sh.task_node)[:T]
    )
    np.testing.assert_array_equal(
        np.asarray(dec_ref.bind_mask), np.asarray(dec_sh.bind_mask)[:T]
    )
