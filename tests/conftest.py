"""Test config: force an 8-device virtual CPU platform before JAX import.

Multi-chip sharding is tested on a virtual CPU mesh (the driver separately
dry-runs the multi-chip path); the real TPU chip is only used by bench.py.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
