"""Test config: force an 8-device virtual CPU platform.

Multi-chip sharding is tested on a virtual CPU mesh (the driver separately
dry-runs the multi-chip path); the real TPU chip is only used by bench.py.

Note: the environment's sitecustomize imports jax at interpreter startup
(registering the TPU platform plugin), so plain env-var assignment here is
too late — jax.config.update before first backend use is required.
"""
import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache, keyed by the generation-aware backend
# fingerprint (a cache shared across machine generations replayed
# mismatched AOT code — round-5 note): repeat suite runs skip the ~15 s
# compiles the larger tests (soak, parity) otherwise pay.
from kube_arbitrator_tpu.platform import enable_persistent_cache as _epc

_epc()
