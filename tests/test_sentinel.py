"""Perf-regression sentinel: host-fingerprinted history, noise-aware
verdicts on synthetic histories (clear regression -> fail, within-noise
jitter -> pass), the canary's must-fire/must-pass contract, and the
small-rung measure path."""
import json

import pytest

from kube_arbitrator_tpu import sentinel
from kube_arbitrator_tpu.sentinel import (
    Verdict,
    append_history,
    compare,
    compare_row,
    exit_code,
    history_row,
    host_fingerprint,
    load_history,
    main,
    rows_from_bench,
)


def _row(metric="full_actions@50000x5000", cycle_ms=600.0, spread=0.1,
         retraces=0, fp="hostA"):
    """A synthetic history row with a given relative p10-p90 spread."""
    half = cycle_ms * spread / 2
    return {
        "schema": 1, "metric": metric, "cycle_ms": cycle_ms,
        "cycle_ms_p10": cycle_ms - half, "cycle_ms_p90": cycle_ms + half,
        "rep_ms": [cycle_ms - half, cycle_ms, cycle_ms + half],
        "retraces": retraces, "fingerprint": fp,
        "cpu_model": "x", "cpu_count": 2, "devices": "cpu",
        "recorded_at": 1.0,
    }


def test_host_fingerprint_stable_and_keyed():
    a, b = host_fingerprint(devices="cpu"), host_fingerprint(devices="cpu")
    assert a["fingerprint"] == b["fingerprint"]
    assert a["fingerprint"] != host_fingerprint(devices="tpu")["fingerprint"]
    assert a["cpu_count"] >= 1


def test_fingerprint_changed_detects_new_host_class():
    """bench.py's baseline-reset warning (the BENCH_r08 trap): a
    non-empty history with zero rows of this host class means the next
    append silently starts a fresh baseline — flag it."""
    from kube_arbitrator_tpu.sentinel import fingerprint_changed, history_row

    host = host_fingerprint(devices="cpu")
    row = history_row("m", 10.0, host=host)
    # empty history: a first-ever run is not a reset
    assert not fingerprint_changed([], host["fingerprint"])
    # same-class rows exist: no reset
    assert not fingerprint_changed([row], host["fingerprint"])
    # only foreign-class rows: the baseline resets
    other = history_row("m", 10.0, host=host_fingerprint(devices="tpu"))
    assert fingerprint_changed([other], host["fingerprint"])
    assert not fingerprint_changed([other, row], host["fingerprint"])


def test_history_roundtrip_skips_torn_lines(tmp_path):
    path = str(tmp_path / "h.jsonl")
    rows = [history_row("m1", 100.0, 95.0, 105.0, [95, 100, 105], 0),
            history_row("m2", 50.0)]
    append_history(path, rows)
    with open(path, "a") as f:
        f.write('{"torn": ')  # SIGKILLed writer mid-append
    loaded = load_history(path)
    assert [r["metric"] for r in loaded] == ["m1", "m2"]
    assert loaded[0]["fingerprint"] == host_fingerprint()["fingerprint"]


def test_clear_regression_fails():
    base = [_row(cycle_ms=600.0, spread=0.1) for _ in range(3)]
    v = compare_row(base, _row(cycle_ms=1250.0, spread=0.1))
    assert v.status == "regression"
    assert exit_code([v]) == 1


def test_within_noise_jitter_passes():
    base = [_row(cycle_ms=600.0, spread=0.3)]
    # +25% is inside the 3x-spread (90%-capped) band
    v = compare_row(base, _row(cycle_ms=750.0))
    assert v.status == "ok"
    assert exit_code([v]) == 0


def test_two_x_slowdown_always_fails_even_on_noisy_history():
    """The margin ceiling is structural: REL_CEIL < 1.0 means a genuine
    2x median slowdown clears the band no matter the recorded spread."""
    for spread in (0.1, 0.5, 0.8, 2.0):
        base = [_row(cycle_ms=600.0, spread=spread) for _ in range(4)]
        v = compare_row(base, _row(cycle_ms=1200.0))
        assert v.status == "regression", (spread, v.detail)


def test_improvement_reported_not_failed():
    base = [_row(cycle_ms=600.0, spread=0.1)]
    v = compare_row(base, _row(cycle_ms=200.0))
    assert v.status == "improved"
    assert exit_code([v]) == 0


def test_other_host_class_is_no_baseline():
    history = [_row(fp="hostA")]
    v = compare(history, [_row(cycle_ms=5000.0, fp="hostB")])[0]
    assert v.status == "no-baseline"
    assert exit_code([v]) == 0


def test_retrace_contaminated_rows_excluded_from_anchor():
    """A recompile-inflated row must not drag the baseline center up
    (masking a regression) when clean rows exist."""
    base = [_row(cycle_ms=600.0), _row(cycle_ms=600.0),
            _row(cycle_ms=5000.0, retraces=3)]
    v = compare_row(base, _row(cycle_ms=1300.0))
    assert v.status == "regression"  # vs the clean 600 center, not 5000
    assert v.baseline_ms == 600.0


def test_rows_from_bench_ladder_and_cadence():
    host = host_fingerprint(devices="cpu")
    ladder = {"metric": "allocate@1000x100", "cycle_ms": 2.5,
              "cycle_ms_p10": 2.4, "cycle_ms_p90": 2.7,
              "rep_ms": [2.4, 2.5, 2.7], "retraces": 0, "value": 9.9,
              "unit": "pods/s", "native_ops": True}
    r = rows_from_bench(ladder, host=host)
    assert r["metric"] == "allocate@1000x100" and r["cycle_ms"] == 2.5
    assert r["source"] == "bench" and r["native_ops"] is True
    cadence = {"metric": "pipeline_cadence_q512@5000x500", "value": 5.3,
               "unit": "x",
               "pipelined": {"period_ms": 32.4,
                             "period_ms_reps": [40.5, 30.0, 32.4]}}
    r2 = rows_from_bench(cadence, host=host)
    assert r2["cycle_ms"] == 32.4 and r2["cycle_ms_p10"] == 30.0
    # error rows (no timing) are skipped, not crashed on
    assert rows_from_bench({"metric": "x", "error": "boom"}, host=host) is None


@pytest.fixture
def seeded_history(tmp_path):
    path = str(tmp_path / "BENCH_HISTORY.jsonl")
    host = host_fingerprint()
    rows = [
        history_row("full_actions@50000x5000", 600.0, 550.0, 680.0,
                    [550, 600, 680], 0, host=host),
        history_row("allocate@1000x100", 2.5, 2.4, 2.7, [2.4, 2.5, 2.7], 0,
                    host=host),
    ]
    append_history(path, rows)
    return path


def test_canary_cli_contract(seeded_history, capsys):
    """The acceptance gate: a seeded synthetic 2x slowdown exits 1, an
    identical-history run exits 0."""
    assert main(["canary", "--history", seeded_history,
                 "--slowdown", "2.0"]) == 1
    out = capsys.readouterr().out
    verdicts = [json.loads(line) for line in out.splitlines()]
    assert all(v["status"] == "regression" for v in verdicts)
    assert main(["canary", "--history", seeded_history,
                 "--slowdown", "1.0"]) == 0
    out = capsys.readouterr().out
    assert all(json.loads(l)["status"] == "ok" for l in out.splitlines())
    # single-metric restriction works; unknown metric is a usage error
    assert main(["canary", "--history", seeded_history, "--slowdown", "2.0",
                 "--metric", "allocate@1000x100"]) == 1
    capsys.readouterr()
    assert main(["canary", "--history", seeded_history, "--slowdown", "2.0",
                 "--metric", "nope"]) == 2
    capsys.readouterr()


def test_canary_empty_history_is_usage_error(tmp_path, capsys):
    assert main(["canary", "--history", str(tmp_path / "missing.jsonl")]) == 2
    capsys.readouterr()


def test_compare_cli_against_row_file(seeded_history, tmp_path, capsys):
    slow = history_row("full_actions@50000x5000", 1400.0, 1300.0, 1500.0)
    row_file = str(tmp_path / "current.jsonl")
    with open(row_file, "w") as f:
        f.write(json.dumps(slow) + "\n")
    assert main(["compare", "--history", seeded_history,
                 "--row", row_file]) == 1
    capsys.readouterr()
    ok = history_row("full_actions@50000x5000", 610.0, 580.0, 640.0)
    with open(row_file, "w") as f:
        f.write(json.dumps(ok) + "\n")
    assert main(["compare", "--history", seeded_history,
                 "--row", row_file]) == 0
    capsys.readouterr()


@pytest.mark.slow
def test_measure_rung_records_comparable_row(tmp_path, capsys):
    """The PERF_SENTINEL lane's probe: a tiny rung measures, appends,
    and a re-measure compares ok against it (same host class, no code
    change in between)."""
    path = str(tmp_path / "h.jsonl")
    rc = main(["measure", "--rung", "400x32", "--actions", "allocate",
               "--reps", "2", "--history", path, "--append"])
    assert rc == 0
    row = load_history(path)[0]
    assert row["cycle_ms"] > 0 and row["metric"].startswith("sentinel:allocate@")
    capsys.readouterr()
