"""Decision audit & fairness accounting plane (utils/audit.py).

Covers the acceptance bar of the audit PR:

* a directed two-queue preemption (cross-queue reclaim) scenario pinned
  to its EXACT preemptor→victim edge set — claimant, victim, phase,
  round;
* audit-on vs audit-off decision parity over full-action worlds (3
  seeds): bit-identical decision tensors, identical actuated streams,
  and ZERO added retraces (the kernels always compute the attribution
  aux; the audit switch is host-side only);
* the fairness ledger's entitlement math; starvation clock + the
  ``starvation`` flight anomaly (hysteresis);
* AuditLog mechanics: ring bound, JSONL append log, corr-id join,
  schema version, the dropped-edge mutation seam;
* the served ``/debug/audit`` routes and promtext conformance of the
  new metric families;
* flight digests carrying eviction-edge counts + top-K fairness rows.
"""
import dataclasses
import json
import types
import urllib.request

import numpy as np

from kube_arbitrator_tpu.api import TaskStatus
from kube_arbitrator_tpu.cache import SimCluster, build_snapshot, generate_cluster
from kube_arbitrator_tpu.cache.decode import decode_decisions
from kube_arbitrator_tpu.framework import Scheduler
from kube_arbitrator_tpu.framework.conf import load_conf
from kube_arbitrator_tpu.ops import schedule_cycle
from kube_arbitrator_tpu.utils.audit import (
    AUDIT_SCHEMA_VERSION,
    AuditLog,
    build_audit_record,
    evict_edge_counts,
    eviction_edges,
    fairness_ledger,
    fairness_top,
)
from kube_arbitrator_tpu.utils.metrics import MetricsRegistry

GB = 1024**3
FULL_CONF = load_conf('actions: "reclaim, allocate, backfill, preempt"\n')


def _result_of(snap, dec):
    """Minimal CycleResult stand-in for the record builders: decoded
    intents ARE the actuated sets on the sequential path."""
    binds, evicts = decode_decisions(snap, dec)
    return types.SimpleNamespace(
        snapshot=snap, decisions=dec, binds=binds, evicts=evicts
    )


def _two_queue_reclaim_world():
    """qb and qc both reclaim from qa's only node (the same directed
    world the batched-turn parity suite pins against the oracle)."""
    sim = SimCluster()
    sim.add_queue("qa", weight=1)
    sim.add_queue("qb", weight=1)
    sim.add_queue("qc", weight=1)
    sim.add_node("n1", cpu_milli=4000, memory=8 * GB)
    ja = sim.add_job("a", queue="qa", creation_ts=1)
    for i in range(4):
        sim.add_task(ja, 1000, GB, status=TaskStatus.RUNNING, node="n1",
                     name=f"a-r{i}", priority=i)
    jb = sim.add_job("b", queue="qb", min_available=1, creation_ts=2)
    sim.add_task(jb, 1000, GB, name="b-p0")
    jc = sim.add_job("c", queue="qc", min_available=1, creation_ts=3)
    sim.add_task(jc, 1000, GB, name="c-p0")
    return sim


def test_two_queue_preemption_exact_edge_set():
    """The known two-queue scenario decodes to its EXACT preemptor→victim
    edge set: each claimant queue takes one distinct victim of qa, in the
    deterministic (queue, job, priority, uid) victim order, both claims in
    round 0 of the reclaim phase."""
    sim = _two_queue_reclaim_world()
    snap = build_snapshot(sim.cluster)
    dec = schedule_cycle(snap.tensors, actions=("reclaim",))
    edges = eviction_edges(snap, dec)
    got = {
        (e["claimant_job"], e["victim"], e["action"], e["phase"], e["round"])
        for e in edges
    }
    # qb pops first (queue uid order), takes the lowest-(priority, uid)
    # victim; qc's turn takes the next — exact, not just count-2
    assert got == {
        ("b", "a-r0", "reclaim", "reclaim", 0),
        ("c", "a-r1", "reclaim", "reclaim", 0),
    }, got
    for e in edges:
        assert e["victim_job"] == "a" and e["victim_queue"] == "qa"
        assert e["node"] == "n1"
        assert e["committed"] and e["actuated"]
    assert evict_edge_counts(dec) == {"reclaim:reclaim": 2}


def test_same_queue_preempt_edges_carry_phase_and_claimant():
    """Preempt phase 1 (inter-job, same queue): the pending gang's edges
    name it as claimant with action=preempt/phase=inter, and the
    evicted_for conditional-commit channel agrees with the edge set."""
    sim = SimCluster()
    sim.add_queue("q", weight=1)
    sim.add_node("n1", cpu_milli=4000, memory=8 * GB)
    low = sim.add_job("low", queue="q", creation_ts=1)
    for i in range(4):
        sim.add_task(low, 1000, GB, status=TaskStatus.RUNNING, node="n1",
                     name=f"low-r{i}", priority=0)
    high = sim.add_job("high", queue="q", min_available=2, creation_ts=2)
    for i in range(2):
        sim.add_task(high, 1000, GB, name=f"high-p{i}", priority=2)
    snap = build_snapshot(sim.cluster)
    dec = schedule_cycle(snap.tensors, actions=("preempt",))
    edges = eviction_edges(snap, dec)
    got = {
        (e["claimant_job"], e["victim"], e["action"], e["phase"], e["round"])
        for e in edges
    }
    # the gang needs exactly 2 slots; victims fall in (priority, uid)
    # order within the node, both in round 0 of the inter-job phase
    assert got == {
        ("high", "low-r0", "preempt", "inter", 0),
        ("high", "low-r1", "preempt", "inter", 0),
    }, got
    assert all(
        e["victim_job"] == "low" and e["committed"] and e["actuated"]
        for e in edges
    )
    assert evict_edge_counts(dec) == {"preempt:inter": 2}


def test_audit_on_off_decision_parity_and_zero_retraces():
    """Audit on vs off over full-action worlds: identical actuated
    streams cycle-for-cycle and ZERO retraces in the audited run once the
    unaudited run warmed the compile caches (3 seeds — the kernel aux is
    always computed, so nothing about the programs differs)."""
    from kube_arbitrator_tpu.utils.profiling import RetraceCounter

    for seed in (0, 1, 2):
        def world():
            return generate_cluster(
                num_nodes=24, num_jobs=10, tasks_per_job=4, num_queues=4,
                seed=seed, node_cpu_milli=4000, node_memory=8 * GB,
                running_fraction=0.4,
            )

        streams = {}
        for audited in (False, True):
            sim = world()
            audit = AuditLog(capacity=16) if audited else None
            sched = Scheduler(sim, config=FULL_CONF, audit=audit)
            stream = []
            with RetraceCounter() as rc:
                for _ in range(3):
                    res = sched.run_once()
                    stream.append((
                        sorted(b.task_uid for b in res.binds),
                        sorted(e.task_uid for e in res.evicts),
                    ))
            streams[audited] = stream
            if audited:
                assert rc.count == 0, (
                    f"audit-on run retraced {rc.count}x (seed {seed})"
                )
                assert len(audit.entries()) == 3
        assert streams[True] == streams[False], f"seed {seed} diverged"


def test_fairness_ledger_entitlement_math():
    """One queue hogging the cluster, one pending: the hog reads over (or
    at) its entitlement, the pending queue under, with deserved following
    the proportion water-fill."""
    sim = SimCluster()
    sim.add_queue("hog", weight=1)
    sim.add_queue("starved", weight=1)
    sim.add_node("n1", cpu_milli=4000, memory=8 * GB)
    jh = sim.add_job("h", queue="hog", creation_ts=1)
    for i in range(4):
        sim.add_task(jh, 1000, 512 * 1024**2, status=TaskStatus.RUNNING,
                     node="n1", name=f"h-r{i}")
    js = sim.add_job("s", queue="starved", min_available=1, creation_ts=2)
    sim.add_task(js, 2000, GB, name="s-p0")
    snap = build_snapshot(sim.cluster)
    dec = schedule_cycle(snap.tensors)  # allocate/backfill only: no evict
    rows = {r["queue"]: r for r in fairness_ledger(snap, dec)}
    hog, starved = rows["hog"], rows["starved"]
    # the hog holds the whole node's cpu; water-fill grants each queue
    # its request-capped share, so the hog is at/over entitlement
    assert hog["share_allocated"] >= hog["share_deserved"] - 1e-6
    assert hog["delta"] >= -1e-6
    # the starved queue deserves a share but holds nothing
    assert starved["share_allocated"] == 0.0
    assert starved["share_deserved"] > 0.0
    assert starved["delta"] < 0.0
    assert starved["pending"] == 1
    top = fairness_top(snap, dec, k=1)
    assert top[0]["queue"] == "starved"  # largest |delta|


def test_starvation_clock_and_flight_anomaly():
    """A pending, under-entitled queue accrues starvation seconds on the
    injectable clock; past the SLO the ``starvation`` flight anomaly
    fires ONCE per episode (hysteresis) and the gauge is exported."""
    from kube_arbitrator_tpu.utils.flightrec import FlightRecorder

    sim = SimCluster()
    sim.add_queue("hog", weight=1)
    sim.add_queue("starved", weight=1)
    sim.add_node("n1", cpu_milli=4000, memory=8 * GB)
    jh = sim.add_job("h", queue="hog", creation_ts=1)
    for i in range(4):
        sim.add_task(jh, 1000, 512 * 1024**2, status=TaskStatus.RUNNING,
                     node="n1", name=f"h-r{i}")
    js = sim.add_job("s", queue="starved", min_available=1, creation_ts=2)
    sim.add_task(js, 2000, GB, name="s-p0")  # can never fit: cpu > node
    snap = build_snapshot(sim.cluster)
    dec = schedule_cycle(snap.tensors)
    result = _result_of(snap, dec)

    clock = {"t": 100.0}
    registry = MetricsRegistry()
    flight = FlightRecorder(capacity=4)
    audit = AuditLog(
        capacity=8, registry=registry, flight=flight, starvation_slo_s=5.0,
        now_fn=lambda: clock["t"],
    )
    anomalies = []
    flight.anomaly = lambda kind, detail="": anomalies.append((kind, detail))
    for step in range(4):
        rec = audit.observe_cycle(step, f"c{step}", clock["t"], result)
        clock["t"] += 4.0
    starv = {r["queue"]: r["starvation_s"] for r in rec.fairness}
    assert starv["starved"] == 12.0  # 3 barren cycles x 4 s
    kinds = [k for k, _ in anomalies]
    assert kinds.count("starvation") == 1, anomalies  # hysteresis: one episode
    assert "starved" in anomalies[0][1]
    g = registry.gauge_value(
        "queue_starvation_seconds", labels={"queue": "starved"}
    )
    assert g == 12.0
    # entitlement gauges exported for both kinds
    assert registry.gauge_value(
        "fairness_share", labels={"queue": "starved", "kind": "deserved"}
    ) > 0.0
    assert registry.gauge_value(
        "fairness_share", labels={"queue": "starved", "kind": "allocated"}
    ) == 0.0


def test_audit_log_ring_jsonl_corr_join_and_drop_seam(tmp_path):
    sim = _two_queue_reclaim_world()
    snap = build_snapshot(sim.cluster)
    dec = schedule_cycle(snap.tensors, actions=("reclaim",))
    result = _result_of(snap, dec)
    path = tmp_path / "audit.jsonl"
    audit = AuditLog(capacity=2, log_path=str(path), registry=MetricsRegistry())
    for i in range(3):
        audit.observe_cycle(i + 1, f"corr-{i + 1}", 1000.0 + i, result)
    # ring bounded at 2, JSONL append-only keeps all 3
    assert [r["seq"] for r in audit.entries()] == [2, 3]
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert [r["seq"] for r in lines] == [1, 2, 3]
    assert all(r["version"] == AUDIT_SCHEMA_VERSION for r in lines)
    rec = audit.by_corr("corr-2")
    assert rec is not None and rec["seq"] == 2
    assert audit.by_corr("corr-1") is None  # rolled out of the ring
    assert len(rec["evictions"]) == 2 and rec["gangs"]["admitted"] == 2
    # the chaos sensitivity seam drops exactly one bind row (needs a
    # world that BINDS: a fitting pending job under the default actions)
    sim2 = SimCluster()
    sim2.add_queue("q")
    sim2.add_node("n1", cpu_milli=4000, memory=8 * GB)
    j = sim2.add_job("j", queue="q", min_available=1)
    for i in range(2):
        sim2.add_task(j, 1000, GB, name=f"j-p{i}")
    snap2 = build_snapshot(sim2.cluster)
    result2 = _result_of(snap2, schedule_cycle(snap2.tensors))
    full = build_audit_record(9, "x", 0.0, result2)
    assert len(full.binds) == 2
    audit.drop_first_edge = True
    mutated = audit.observe_cycle(9, "x", 0.0, result2)
    assert len(mutated.binds) == len(full.binds) - 1


def test_debug_audit_routes_and_promtext(tmp_path):
    from kube_arbitrator_tpu.obs import serve_obs
    from kube_arbitrator_tpu.utils.metrics import metrics
    from tests.test_obs import check_promtext

    sim = _two_queue_reclaim_world()
    snap = build_snapshot(sim.cluster)
    dec = schedule_cycle(snap.tensors, actions=("reclaim",))
    audit = AuditLog(capacity=4)  # process-wide registry: families served
    audit.observe_cycle(1, "corr-a", 1.0, _result_of(snap, dec))
    server, _t, url = serve_obs(audit=audit)
    try:
        body = json.load(urllib.request.urlopen(url + "/debug/audit", timeout=10))
        assert body["schema_version"] == AUDIT_SCHEMA_VERSION
        assert len(body["records"]) == 1
        assert body["records"][0]["evictions"]
        one = json.load(
            urllib.request.urlopen(url + "/debug/audit/corr-a", timeout=10)
        )
        assert one["seq"] == 1
        try:
            urllib.request.urlopen(url + "/debug/audit/nope", timeout=10)
            assert False, "unknown corr must 404"
        except urllib.error.HTTPError as err:
            assert err.code == 404
        text = urllib.request.urlopen(url + "/metrics", timeout=10).read().decode()
        for fam in ("audit_records_total", "fairness_share",
                    "queue_starvation_seconds", "evictions_attributed_total"):
            assert fam in text, fam
        check_promtext(text)
    finally:
        server.shutdown()
    assert (
        metrics().counter_value(
            "evictions_attributed_total",
            labels={"action": "reclaim", "phase": "reclaim"},
        )
        >= 2
    )


def test_flight_digests_carry_audit_channels():
    from kube_arbitrator_tpu.utils.flightrec import FlightRecorder

    def world():
        return generate_cluster(
            num_nodes=16, num_jobs=6, tasks_per_job=4, num_queues=2, seed=0,
            node_cpu_milli=4000, node_memory=8 * GB, running_fraction=0.3,
        )

    flight = FlightRecorder(capacity=4)
    sched = Scheduler(
        sim=world(), config=FULL_CONF, flight=flight, audit=AuditLog(capacity=4)
    )
    sched.run(max_cycles=2, until_idle=False)
    rec = flight.last()
    assert "evict_edges" in rec.digests
    assert isinstance(rec.digests["fairness_top"], list)
    assert rec.digests["fairness_top"], "digest must carry ledger rows"
    row = rec.digests["fairness_top"][0]
    assert {"queue", "share_deserved", "share_allocated", "delta",
            "pending"} <= set(row)
    # flight WITHOUT the audit plane keeps its cheap footprint: edge
    # counts (one bincount) stay, the O(T) ledger rows do not
    flight2 = FlightRecorder(capacity=4)
    sched2 = Scheduler(sim=world(), config=FULL_CONF, flight=flight2)
    sched2.run(max_cycles=1, until_idle=False)
    rec2 = flight2.last()
    assert "evict_edges" in rec2.digests
    assert rec2.digests["fairness_top"] == []


def test_pipelined_cycles_audit_with_actuated_sets():
    """run_pipelined records one audit record per committed epoch, and
    the record's bind rows equal the ACTUATED (post-revalidation) set."""
    sim = generate_cluster(
        num_nodes=16, num_jobs=8, tasks_per_job=4, num_queues=2, seed=3,
        running_fraction=0.3,
    )
    audit = AuditLog(capacity=32)
    sched = Scheduler(sim, arena=True, audit=audit)
    cycles = sched.run_pipelined(max_cycles=6, until_idle=False)
    recs = audit.entries()
    assert len(recs) == cycles
    total_binds = sum(s.binds for s in sched.history)
    actuated_rows = sum(
        1 for r in recs for b in r["binds"] if b["actuated"]
    )
    assert actuated_rows == total_binds
    assert total_binds > 0
