"""Snapshot tensorization tests: padding, units, equivalence classes."""
import numpy as np

from kube_arbitrator_tpu.api import TaskStatus, Taint, Toleration
from kube_arbitrator_tpu.cache import SimCluster, build_snapshot


def _mk_basic():
    sim = SimCluster()
    sim.add_queue("default", weight=2)
    sim.add_node("n1", cpu_milli=4000, memory=8 * 1024**3)
    sim.add_node("n2", cpu_milli=2000, memory=4 * 1024**3)
    j = sim.add_job("j1", queue="default", min_available=2)
    sim.add_task(j, 1000, 1024**3)
    sim.add_task(j, 1000, 1024**3)
    return sim


def test_shapes_and_padding():
    snap = build_snapshot(_mk_basic().cluster)
    t = snap.tensors
    assert t.num_nodes == 128  # padded to lane width
    assert t.num_tasks == 8
    assert int(t.node_valid.sum()) == 2
    assert int(t.task_valid.sum()) == 2
    assert bool(t.task_valid[0]) and not bool(t.task_valid[2])


def test_device_units_and_idle():
    snap = build_snapshot(_mk_basic().cluster)
    t = snap.tensors
    # memory is in MiB on device
    np.testing.assert_allclose(t.node_alloc[0], [4000.0, 8192.0, 0.0, 4000.0])  # attach x100
    np.testing.assert_allclose(t.task_resreq[0], [1000.0, 1024.0, 0.0, 0.0])


def test_running_task_affects_idle_and_counts():
    sim = _mk_basic()
    j2 = sim.add_job("j2")
    sim.add_task(j2, 1000, 1024**3, status=TaskStatus.RUNNING, node="n1")
    snap = build_snapshot(sim.cluster)
    t = snap.tensors
    n1 = next(n.ordinal for n in snap.index.nodes if n.name == "n1")
    np.testing.assert_allclose(t.node_idle[n1], [3000.0, 7168.0, 0.0, 4000.0])
    assert int(t.node_num_tasks[n1]) == 1
    # the running task's node ordinal is recorded
    running = [i for i, ti in enumerate(snap.index.tasks) if ti.status == TaskStatus.RUNNING]
    assert len(running) == 1
    assert int(t.task_node[running[0]]) == n1


def test_equivalence_classes_selector_taints():
    sim = SimCluster()
    sim.add_queue("default")
    sim.add_node("gpu-node", labels={"accel": "tpu"}, taints=[Taint("dedicated", "ml", "NoSchedule")])
    sim.add_node("plain-node")
    j = sim.add_job("j1")
    t_sel = sim.add_task(j, 100, 0, node_selector={"accel": "tpu"})
    t_tol = sim.add_task(
        j, 100, 0, node_selector={"accel": "tpu"},
        tolerations=[Toleration(key="dedicated", operator="Equal", value="ml", effect="NoSchedule")],
    )
    t_plain = sim.add_task(j, 100, 0)
    snap = build_snapshot(sim.cluster)
    t = snap.tensors
    cf = np.asarray(t.class_fit)
    ords = {ti.uid: ti.ordinal for ti in snap.index.tasks}
    nords = {ni.name: ni.ordinal for ni in snap.index.nodes}
    tk = np.asarray(t.task_klass)
    nk = np.asarray(t.node_klass)

    def fits(task, node):
        return bool(cf[tk[ords[task.uid]], nk[nords[node]]])

    # selector matches gpu-node but taint not tolerated -> no fit
    assert not fits(t_sel, "gpu-node")
    # toleration + selector -> fits gpu-node only
    assert fits(t_tol, "gpu-node")
    assert not fits(t_tol, "plain-node")  # selector mismatch
    # plain task fits the plain node, not the tainted one
    assert fits(t_plain, "plain-node")
    assert not fits(t_plain, "gpu-node")


def test_host_ports_bitmasks():
    sim = SimCluster()
    sim.add_queue("default")
    sim.add_node("n1")
    j = sim.add_job("j1")
    t1 = sim.add_task(j, 100, 0, host_ports=[8080])
    t2 = sim.add_task(j, 100, 0, host_ports=[8080, 9090], status=TaskStatus.RUNNING, node="n1")
    snap = build_snapshot(sim.cluster)
    t = snap.tensors
    o1 = next(ti.ordinal for ti in snap.index.tasks if ti.uid == t1.uid)
    n1 = next(ni.ordinal for ni in snap.index.nodes if ni.name == "n1")
    # node n1's port mask includes t2's ports; t1 conflicts on 8080
    conflict = np.bitwise_and(np.asarray(t.task_ports[o1]), np.asarray(t.node_ports[n1]))
    assert conflict.any()


def test_others_usage():
    sim = _mk_basic()
    sim.add_other_task("n2", cpu_milli=500, memory=1024**3)
    snap = build_snapshot(sim.cluster)
    np.testing.assert_allclose(snap.tensors.others_used, [500.0, 1024.0, 0.0, 0.0])
