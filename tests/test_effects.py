"""KAT-EFF effect budgets: seeded-mutation tests (each fixture fires
exactly its own rule across ALL families), the interprocedural
propagation shapes, the neutrality-taint pass, the real-tree smoke
against the committed baseline, and the artifact-dir anchoring fix."""
import json
import os
import pathlib
import textwrap

import pytest

from kube_arbitrator_tpu.analysis import ALL_RULES, analyze_paths
from kube_arbitrator_tpu.analysis.rules import RULES_BY_FAMILY

REPO = pathlib.Path(__file__).resolve().parents[1]
EFF = (RULES_BY_FAMILY["KAT-EFF"],)


def run_on(tmp_path, name, source, rules=ALL_RULES):
    f = tmp_path / name
    f.write_text(textwrap.dedent(source))
    _, findings = analyze_paths([str(f)], rules)
    return findings


def rule_ids(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# KAT-EFF-001 — per-element construction in a hot loop


def test_eff001_construction_in_decode_hot_loop(tmp_path):
    findings = run_on(
        tmp_path,
        "sess.py",
        """
        class Session:
            def decode_phase(self, snap, dec):
                out = []
                for i in dec.task_status.tolist():
                    out.append(PodGroupCondition(i))
                return out
        """,
    )
    assert rule_ids(findings) == {"KAT-EFF-001"}
    assert "PodGroupCondition" in findings[0].message


def test_eff001_via_self_method_expansion(tmp_path):
    # the Session._close shape: the loop body calls a same-class helper
    # whose construction counts against the caller's stage
    findings = run_on(
        tmp_path,
        "sess.py",
        """
        class Session:
            def _close(self, snap, dec):
                out = {}
                for job in snap.index.jobs:
                    out[job.uid] = self._status(job)
                return out

            def _status(self, job):
                return PodGroupStatus(job)
        """,
    )
    assert rule_ids(findings) == {"KAT-EFF-001"}
    assert "via `Session._status`" in findings[0].message


def test_eff001_via_hot_argument_propagation(tmp_path):
    # the decode_decisions -> _build_intents shape: a .tolist() product
    # fed to a module helper materializes the helper's param loop
    findings = run_on(
        tmp_path,
        "dec.py",
        """
        class Session:
            def decode_phase(self, snap, dec):
                rows = dec.bind_idx.tolist()
                return build(rows)

        def build(rows):
            return [Intent(r) for r in rows]
        """,
    )
    assert rule_ids(findings) == {"KAT-EFF-001"}
    assert "via `build`" in findings[0].message


def test_eff001_silent_outside_mapped_stages(tmp_path):
    # same loop + construction, but the function is no stage: budgets
    # bind to the pipeline, not to arbitrary code
    findings = run_on(
        tmp_path,
        "free.py",
        """
        def helper(dec):
            return [Intent(i) for i in dec.task_status.tolist()]
        """,
        rules=EFF,
    )
    assert findings == []


# ---------------------------------------------------------------------------
# KAT-EFF-002 — undeclared host sync in decide/decode


def test_eff002_undeclared_item_in_decide(tmp_path):
    findings = run_on(
        tmp_path,
        "sess.py",
        """
        class Session:
            def decide_phase(self, snap, st):
                n = st.bind_count.item()
                return n
        """,
    )
    assert rule_ids(findings) == {"KAT-EFF-002"}
    assert "`item`" in findings[0].message


def test_eff002_declared_syncs_are_clean(tmp_path):
    # decode's budget declares tolist/asarray/int; decide's declares
    # block_until_ready/int — the sanctioned mechanisms stay silent
    findings = run_on(
        tmp_path,
        "sess.py",
        """
        import numpy as np

        class Session:
            def decide_phase(self, snap, st):
                dec = go(st)
                dec.task_node.block_until_ready()
                return dec

            def decode_phase(self, snap, dec):
                n = int(dec.bind_count)
                return np.asarray(dec.task_node)
        """,
        rules=EFF,
    )
    assert findings == []


# ---------------------------------------------------------------------------
# KAT-EFF-003 — blocking on a latency-critical role, disjoint from KAT-LCK-002


def test_eff003_sleep_on_ingest_thread(tmp_path):
    findings = run_on(
        tmp_path,
        "live.py",
        """
        import time

        class LiveCache:
            def _dispatch(self, ev):
                time.sleep(0.1)
        """,
    )
    assert rule_ids(findings) == {"KAT-EFF-003"}


def test_eff003_disjoint_from_lck002_under_lock(tmp_path):
    # the SAME call under a lockish with is KAT-LCK-002's finding and
    # must NOT double-report as KAT-EFF-003
    findings = run_on(
        tmp_path,
        "live.py",
        """
        import threading
        import time

        class LiveCache:
            def __init__(self):
                self._lock = threading.Lock()

            def _dispatch(self, ev):
                with self._lock:
                    time.sleep(0.1)
        """,
    )
    assert rule_ids(findings) == {"KAT-LCK-002"}


# ---------------------------------------------------------------------------
# KAT-EFF-004 — unbounded growth of a module-level container


def test_eff004_module_append_in_hot_loop(tmp_path):
    findings = run_on(
        tmp_path,
        "sess.py",
        """
        SEEN = []

        class Session:
            def close_phase(self, snap, dec):
                for uid in dec.task_node.tolist():
                    SEEN.append(uid)
        """,
    )
    assert rule_ids(findings) == {"KAT-EFF-004"}


def test_eff004_local_append_is_clean(tmp_path):
    findings = run_on(
        tmp_path,
        "sess.py",
        """
        class Session:
            def close_phase(self, snap, dec):
                out = []
                for uid in dec.task_node.tolist():
                    out.append(uid)
                return out
        """,
        rules=EFF,
    )
    assert findings == []


# ---------------------------------------------------------------------------
# KAT-EFF-010 — decision-neutrality taint


def test_eff010_neutral_field_into_selection(tmp_path):
    findings = run_on(
        tmp_path,
        "ops.py",
        """
        import jax.numpy as jnp

        def my_action(st, state):
            victim = jnp.argmax(state.evict_claimant)
            return victim

        ACTION_KERNELS = {"my": my_action}
        """,
    )
    assert rule_ids(findings) == {"KAT-EFF-010"}
    assert "evict_claimant" in findings[0].message


def test_eff010_neutral_field_into_other_output(tmp_path):
    # routed through a local, into a DIFFERENT keyword of the state
    # rebuild: the taint must survive the assignment hop
    findings = run_on(
        tmp_path,
        "ops.py",
        """
        import dataclasses
        import jax.numpy as jnp

        def my_action(st, state):
            pressure = state.rounds_gated.astype(jnp.float32)
            return dataclasses.replace(state, progress=pressure)

        ACTION_KERNELS = {"my": my_action}
        """,
    )
    assert rule_ids(findings) == {"KAT-EFF-010"}
    assert "rounds_gated" in findings[0].message


def test_eff010_same_name_carry_is_clean(tmp_path):
    # the repo's real idiom: neutral fields carried forward into
    # THEMSELVES (including conditionals mixing decision-bearing state)
    findings = run_on(
        tmp_path,
        "ops.py",
        """
        import dataclasses
        import jax.numpy as jnp

        def my_action(st, state, evict, gated):
            return dataclasses.replace(
                state,
                evict_claimant=jnp.where(evict, st.task_job, state.evict_claimant),
                evict_round=jnp.where(evict, state.rounds, state.evict_round),
                rounds_gated=state.rounds_gated + gated,
            )

        ACTION_KERNELS = {"my": my_action}
        """,
        rules=EFF,
    )
    assert findings == []


def test_eff010_state_rebuild_does_not_smear_taint(tmp_path):
    # `state = replace(state, evict_round=...)` must not taint every
    # later read of `state` (the aggregate is a barrier; flows are
    # checked field-wise at each sink)
    findings = run_on(
        tmp_path,
        "ops.py",
        """
        import dataclasses
        import jax.numpy as jnp

        def my_action(st, state, evict):
            state = dataclasses.replace(
                state,
                evict_round=jnp.where(evict, state.rounds, state.evict_round),
            )
            score = state.task_status + 1
            return dataclasses.replace(state, progress=score)

        ACTION_KERNELS = {"my": my_action}
        """,
        rules=EFF,
    )
    assert findings == []


# ---------------------------------------------------------------------------
# real-tree smoke


def test_real_tree_findings_match_committed_baseline(monkeypatch):
    """The real tree carries ZERO KAT-EFF findings and the committed
    baseline is empty — the decode intent floors retired when
    `_build_intents` gave way to the columnar `decode_batch` path, and
    the close-census status-object floors retired when the explain pass
    vectorized (`_close` batches `_fit_messages` over the first
    unplaced row per job instead of calling `explain_job` inside the
    snapshot-index walk).  A finding here means a new hot-loop
    allocation crept in: either fix it or justify it IN the baseline,
    never by widening this assert."""
    from kube_arbitrator_tpu.analysis.report import load_baseline

    monkeypatch.chdir(REPO)  # fingerprints embed CWD-relative paths
    _, findings = analyze_paths([str(REPO / "kube_arbitrator_tpu")], EFF)
    assert findings == [], "\n".join(f.format() for f in findings)
    baseline = load_baseline(str(REPO / ".kat-baseline.json"))
    assert sorted(baseline) == []


# ---------------------------------------------------------------------------
# artifact-dir anchoring (cache + sanitizer dumps)


def test_resolve_anchors_relative_paths(tmp_path, monkeypatch):
    from kube_arbitrator_tpu.analysis import artifacts

    a, b = tmp_path / "a", tmp_path / "b"
    a.mkdir(), b.mkdir()
    monkeypatch.setenv(artifacts.ENV_VAR, str(a))
    monkeypatch.chdir(b)
    assert artifacts.resolve(".kat-cache") == str(a / ".kat-cache")
    # absolute paths pass through untouched
    assert artifacts.resolve(str(b / "x")) == str(b / "x")
    # without the env var the IMPORT-time cwd anchors, not the current one
    monkeypatch.delenv(artifacts.ENV_VAR)
    assert artifacts.resolve(".kat-cache") == os.path.join(
        artifacts._IMPORT_CWD, ".kat-cache"
    )


def test_cache_writes_to_anchor_not_cwd(tmp_path, monkeypatch):
    from kube_arbitrator_tpu.analysis.cache import AnalysisCache

    anchor, elsewhere = tmp_path / "anchor", tmp_path / "elsewhere"
    anchor.mkdir(), elsewhere.mkdir()
    monkeypatch.setenv("KAT_ARTIFACT_ROOT", str(anchor))
    monkeypatch.chdir(elsewhere)
    cache = AnalysisCache(".kat-cache")
    cache.put_findings("f.py", "k", [])
    cache.flush()
    assert (anchor / ".kat-cache" / "findings.json").exists()
    assert not (elsewhere / ".kat-cache").exists()
    # and a fresh instance from yet another CWD warms from the same store
    monkeypatch.chdir(tmp_path)
    assert AnalysisCache(".kat-cache").get_findings("f.py", "k") == []


def test_sanitizer_dump_lands_at_anchor(tmp_path, monkeypatch):
    from kube_arbitrator_tpu.analysis.rules.lockorder import LockGraph
    from kube_arbitrator_tpu.analysis.sanitizer import dump_artifact

    anchor, elsewhere = tmp_path / "anchor", tmp_path / "elsewhere"
    anchor.mkdir(), elsewhere.mkdir()
    monkeypatch.setenv("KAT_ARTIFACT_ROOT", str(anchor))
    monkeypatch.chdir(elsewhere)
    graph = LockGraph()
    graph.add_site("x.a", "m.py", 1)
    p = dump_artifact("evidence", graph, {"edges": []})
    assert p == str(anchor / "evidence" / "sanitizer-0001.json")
    assert json.loads((anchor / "evidence" / "sanitizer-0001.json").read_text())
    assert not (elsewhere / "evidence").exists()


# ---------------------------------------------------------------------------
# CLI surface


def test_cli_explain_prints_rationale(capsys):
    from kube_arbitrator_tpu.analysis.cli import main

    assert main(["--explain", "KAT-EFF-001"]) == 0
    out = capsys.readouterr().out
    assert "KAT-EFF-001" in out and "Fix pattern:" in out
    assert main(["--explain", "KAT-NOPE-999"]) == 2


def test_cli_lists_eff_family(capsys):
    from kube_arbitrator_tpu.analysis.cli import main

    assert main(["--list-rules"]) == 0
    assert "KAT-EFF" in capsys.readouterr().out
