"""Kernel cost attribution: runtime retrace metrics, the estimated-vs-
measured cost table over real scheduler cycles, the /debug/kernels
endpoint, and the injectable clock (chaos-plane determinism seam)."""
import json
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from kube_arbitrator_tpu.obs import serve_obs
from kube_arbitrator_tpu.utils import profiling
from kube_arbitrator_tpu.utils.metrics import METRIC_HELP, metrics
from kube_arbitrator_tpu.utils.profiling import (
    KernelProfiler,
    RetraceCounter,
    profiler,
    shape_key,
)
from tests.test_obs import check_promtext


@pytest.fixture
def clean_profiler():
    prof = profiler()
    prof.reset()
    prof.enable()
    metrics().reset()
    yield prof
    prof.enable(False)
    prof.reset()


def _force_compile(tag: int):
    """A jit the process has never compiled (fresh lambda + unique shape)."""
    fn = jax.jit(lambda x: x * 2 + tag)
    fn(jnp.ones(3 + tag)).block_until_ready()


def test_retrace_counter_window_semantics():
    """The bench-style armed window (moved here from bench.py): compiles
    inside the window count, compiles outside do not."""
    with RetraceCounter() as rt:
        _force_compile(101)
    outside = rt.count
    _force_compile(102)  # window closed: must not count
    assert outside >= 1
    assert rt.count == outside


def test_retraces_attributed_to_active_stage(clean_profiler):
    """A compile firing inside a stage scope lands in
    xla_retraces_total{fn=<stage>} and xla_compile_seconds."""
    with clean_profiler.stage_scope("allocate"):
        _force_compile(201)
    _force_compile(202)  # no stage active -> fn="other"
    m = metrics()
    assert m.counter_value("xla_retraces_total", {"fn": "allocate"}) >= 1
    assert m.counter_value("xla_retraces_total", {"fn": "other"}) >= 1
    hist = m.histogram("xla_compile_seconds")
    assert hist is not None and hist.n >= 2
    text = m.render()
    check_promtext(text)
    assert "# HELP kube_arbitrator_tpu_xla_retraces_total" in text
    for fam in ("xla_retraces_total", "xla_compile_seconds",
                "slo_burn_rate", "slo_burn_alerts_total"):
        assert fam in METRIC_HELP, fam


def test_disabled_profiler_stage_scope_is_noop():
    prof = KernelProfiler()
    with prof.stage_scope("allocate"):
        assert profiling.current_stage() is None  # null scope: no TLS write
    assert prof.table()["shapes"] == {}


def test_staged_cycles_fill_cost_table(clean_profiler):
    """Real scheduler cycles with the profiler on (tracing OFF — the
    profiler alone must route decides through the staged runner) fill
    measured ms and HLO estimates per action at the pack's shape key."""
    from kube_arbitrator_tpu.cache import build_snapshot, generate_cluster
    from kube_arbitrator_tpu.framework import Scheduler

    sim = generate_cluster(num_nodes=16, num_jobs=4, tasks_per_job=4,
                           num_queues=2, seed=11)
    key = shape_key(build_snapshot(sim.cluster).tensors)
    sched = Scheduler(sim)
    sched.run(max_cycles=2, until_idle=False)
    table = clean_profiler.table()
    assert key in table["shapes"], table["shapes"].keys()
    stages = table["shapes"][key]
    assert "allocate" in stages and "open_session" in stages
    alloc = stages["allocate"]
    assert alloc["measured"]["count"] >= 2
    assert alloc["measured"]["mean_ms"] > 0
    est = alloc["estimate"]
    assert est.get("flops", 0) > 0, est
    assert est.get("bytes_accessed", 0) > 0, est
    assert alloc["gflops_per_s"] >= 0
    # the scheduler still recorded the action histograms (staged path)
    assert metrics().histogram(
        "kernel_action_duration_seconds", {"action": "allocate"}
    ).n >= 2


def test_debug_kernels_endpoint_serves_table(clean_profiler):
    clean_profiler.record_measured("allocate", "T64xN16xQ2xJ8xG8", 3.5, 2)
    server, _t, url = serve_obs(kernel_profiler=clean_profiler)
    try:
        with urllib.request.urlopen(url + "/debug/kernels", timeout=10) as r:
            assert r.status == 200
            body = json.load(r)
    finally:
        server.shutdown()
    stage = body["shapes"]["T64xN16xQ2xJ8xG8"]["allocate"]
    assert stage["measured"]["last_ms"] == 3.5
    assert stage["measured"]["rounds_total"] == 2


def test_now_fn_injectable_for_virtual_clock():
    """The chaos plane's VirtualClock seam: every timestamp the profiler
    stamps comes from the injected clock, so replays are byte-stable."""
    prof = KernelProfiler(now_fn=lambda: 777.0)
    prof.enable()
    prof.record_measured("allocate", "k", 1.0)
    est = prof._estimate_one(lambda: (_ for _ in ()).throw(RuntimeError("x")))
    assert est["estimated_at"] == 777.0
    table = prof.table()
    assert table["generated_at"] == 777.0
    assert table["shapes"]["k"]["allocate"]["measured"]["last_ts"] == 777.0
    clock = [1.0]
    prof.set_now_fn(lambda: clock[0])
    clock[0] = 9.0
    prof.record_measured("allocate", "k", 2.0)
    assert prof.table()["shapes"]["k"]["allocate"]["measured"]["last_ts"] == 9.0


def test_staged_evictive_cycle_records_phase_split_and_gated_rounds(clean_profiler):
    """An evictive staged cycle with the profiler on serves the per-round
    preempt phase-A attribution row (``preempt:phase_a`` pseudo-stage:
    ``phase_a_full_ms`` / ``phase_a_gated_ms`` — full-vs-gated is the
    round gate's per-round saving) and carries the ``rounds_gated_total``
    aggregate on the evictive stages, so /debug/kernels can attribute
    gate hits vs full recomputes."""
    from kube_arbitrator_tpu.cache import build_snapshot, generate_cluster
    from kube_arbitrator_tpu.ops.cycle import schedule_cycle_staged

    GB = 1024 ** 3
    sim = generate_cluster(num_nodes=16, num_jobs=24, tasks_per_job=2,
                           num_queues=4, seed=3, node_cpu_milli=4000,
                           node_memory=8 * GB, running_fraction=0.6)
    st = build_snapshot(sim.cluster).tensors
    key = shape_key(st)
    schedule_cycle_staged(
        st, actions=("reclaim", "allocate", "backfill", "preempt")
    )
    stages = clean_profiler.table()["shapes"][key]
    pre = stages["preempt"]["measured"]
    assert pre["count"] == 1
    assert pre["rounds_total"] >= 1
    assert "rounds_gated_total" in pre  # the gated variant aggregate
    split = stages["preempt:phase_a"]["estimate"]
    assert split["phase_a_full_ms"] > 0, split
    assert split["phase_a_gated_ms"] > 0, split
