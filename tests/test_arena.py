"""Incremental snapshot plane (cache/arena.py): the byte-identity
contract under randomized mutation streams, the structural fallback
triggers, the device-resident pack, and the RPC pack-reuse protocol.

The load-bearing test is the randomized equivalence stream: after EVERY
step of generated bind/evict/add/delete/resync sequences the arena's
incremental pack must be byte-identical to a fresh ``build_snapshot`` —
identical packs imply bit-identical decisions, which is the whole
correctness argument for the delta path.
"""
import dataclasses
import random

import numpy as np
import pytest

from kube_arbitrator_tpu.api import TaskStatus
from kube_arbitrator_tpu.cache import build_snapshot, generate_cluster
from kube_arbitrator_tpu.cache.arena import (
    ArenaDivergence,
    SnapshotArena,
    _pad_rows,
    _scatter_copy,
)
from kube_arbitrator_tpu.cache.sim import BindIntent, EvictIntent, SimCluster
from kube_arbitrator_tpu.cache.snapshot import SnapshotTensors


def assert_packs_identical(a: SnapshotTensors, b: SnapshotTensors, ctx=""):
    for f in dataclasses.fields(SnapshotTensors):
        x, y = getattr(a, f.name), getattr(b, f.name)
        if f.metadata.get("static"):
            assert x == y, f"{ctx}: static {f.name}: {x} != {y}"
            continue
        xa, ya = np.asarray(x), np.asarray(y)
        assert xa.dtype == ya.dtype and xa.shape == ya.shape, (
            f"{ctx}: {f.name}: {xa.dtype}{xa.shape} != {ya.dtype}{ya.shape}"
        )
        assert np.array_equal(xa, ya), (
            f"{ctx}: {f.name}: {int((xa != ya).sum())} cells differ"
        )


def tasks_by_status(sim, status):
    return [
        t for j in sim.cluster.jobs.values() for t in j.tasks.values()
        if t.status == status
    ]


def feasible_bind(sim, rng):
    """One (pending task, node with room) pair, or None."""
    pend = tasks_by_status(sim, TaskStatus.PENDING)
    if not pend:
        return None
    t = rng.choice(pend)
    nodes = list(sim.cluster.nodes.values())
    rng.shuffle(nodes)
    for n in nodes:
        if (n.idle - t.resreq >= -1e-6).all() and len(n.tasks) < n.max_tasks:
            return BindIntent(t.uid, n.name)
    return None


# ---------------------------------------------------------------------------
# the randomized mutation-stream equivalence test


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_mutation_stream_equivalence(seed):
    """After every step of a random bind/evict/add/delete/resync stream,
    the incremental pack == a fresh full rebuild, byte for byte."""
    rng = random.Random(seed)
    sim = generate_cluster(
        num_nodes=12, num_jobs=6, tasks_per_job=6,
        num_queues=2 + seed, seed=seed, running_fraction=0.4,
    )
    arena = SnapshotArena(sim, verify_every=0)

    def step_bind():
        b = feasible_bind(sim, rng)
        if b is not None:
            sim.apply_binds([b])

    def step_bind_failure():
        b = feasible_bind(sim, rng)
        if b is not None:
            sim.binder.fail_uids = {b.task_uid}
            sim.apply_binds([b])          # diverts to the resync FIFO
            sim.binder.fail_uids = set()
            sim.process_resync()          # repairs; emits task deltas

    def step_evict():
        running = tasks_by_status(sim, TaskStatus.RUNNING)
        if running:
            sim.apply_evicts([EvictIntent(rng.choice(running).uid)])

    def step_add_task():
        job = rng.choice(list(sim.cluster.jobs.values()))
        sim.add_task(job, 400, 512 * 1024**2, priority=rng.randrange(3))

    def step_add_job():
        name = f"rand-job-{rng.randrange(10**6)}"
        j = sim.add_job(name, queue=rng.choice(list(sim.cluster.queues)))
        sim.add_task(j, 200, 256 * 1024**2)

    def step_delete_job():
        # pick a job whose tasks are all terminal-or-pending; evict-free
        # deletion path: mark deleted, then GC with delay elapsed
        jobs = [
            j for j in sim.cluster.jobs.values()
            if all(t.status == TaskStatus.PENDING for t in j.tasks.values())
        ]
        if jobs:
            j = rng.choice(jobs)
            for t in j.tasks.values():
                t.status = TaskStatus.SUCCEEDED
            # direct status flip is not an emitted delta: tell the arena
            for t in j.tasks.values():
                arena.task_dirty(t.uid)
            sim.delete_job(j.uid, now=0.0)
            sim.collect_garbage(now=10.0)

    def step_add_node():
        sim.add_node(f"rand-node-{rng.randrange(10**6)}", cpu_milli=16000,
                     memory=32 * 1024**3)

    def step_cordon():
        n = rng.choice(list(sim.cluster.nodes.values()))
        n.unschedulable = not n.unschedulable
        arena.node_dirty(n.name)  # node_updated delta

    steps = [step_bind, step_bind, step_evict, step_add_task, step_cordon,
             step_bind_failure, step_add_job, step_delete_job, step_add_node]
    for i in range(60):
        rng.choice(steps)()
        snap = arena.snapshot()
        fresh = build_snapshot(sim.cluster)
        assert_packs_identical(
            snap.tensors, fresh.tensors,
            ctx=f"seed {seed} step {i} (rebuild={arena.last_rebuild_reason})",
        )


def test_periodic_verify_catches_unpublished_mutation():
    """A backend mutation that never reaches the delta sink must be caught
    by the every-Nth-pack epoch check, not silently served forever."""
    sim = generate_cluster(num_nodes=8, num_jobs=3, tasks_per_job=4,
                           num_queues=2, seed=9)
    arena = SnapshotArena(sim, verify_every=2)
    arena.snapshot()
    # mutate behind the arena's back: no emission
    t = tasks_by_status(sim, TaskStatus.PENDING)[0]
    t.priority += 7
    arena.snapshot()  # delta pack (stale, but nothing marked it dirty)
    with pytest.raises(ArenaDivergence, match="task_priority"):
        arena.snapshot()  # the verify pack
    # the divergence poisons the arena into a rebuild: next pack is clean
    snap = arena.snapshot()
    assert arena.last_rebuild_reason == "divergence"
    assert_packs_identical(snap.tensors, build_snapshot(sim.cluster).tensors)


# ---------------------------------------------------------------------------
# structural fallback triggers


def _mini_sim():
    sim = SimCluster()
    sim.add_queue("default")
    sim.add_node("n1", cpu_milli=8000, memory=16 * 1024**3)
    j = sim.add_job("j1", queue="default")
    sim.add_task(j, 1000, 1024**3)
    sim.add_task(j, 1000, 1024**3, status=TaskStatus.RUNNING, node="n1")
    return sim


def test_structural_fallback_reasons():
    sim = _mini_sim()
    arena = SnapshotArena(sim, verify_every=0)
    arena.snapshot()
    assert arena.last_rebuild_reason == "seed"
    j = sim.cluster.jobs["j1"]
    sim.add_task(j, 500, 1024**3)  # emits structural("task_added")
    arena.snapshot()
    assert arena.last_rebuild_reason == "task_added"
    # steady pack after: delta path again
    arena.snapshot()
    assert arena.last_rebuild_reason is None


def test_signature_change_falls_back():
    """A dirty task whose predicate signature changed cannot be row-
    refreshed (class ids are first-occurrence-ordered) — full rebuild."""
    sim = _mini_sim()
    arena = SnapshotArena(sim, verify_every=0)
    arena.snapshot()
    t = tasks_by_status(sim, TaskStatus.PENDING)[0]
    t.node_selector = {"accel": "tpu"}
    arena.task_dirty(t.uid)
    snap = arena.snapshot()
    assert arena.last_rebuild_reason == "predicate_signature"
    assert_packs_identical(snap.tensors, build_snapshot(sim.cluster).tensors)


def test_port_universe_change_falls_back():
    sim = _mini_sim()
    arena = SnapshotArena(sim, verify_every=0)
    arena.snapshot()
    t = tasks_by_status(sim, TaskStatus.PENDING)[0]
    t.host_ports = (8080,)
    arena.task_dirty(t.uid)
    snap = arena.snapshot()
    assert arena.last_rebuild_reason == "port_universe"
    assert_packs_identical(snap.tensors, build_snapshot(sim.cluster).tensors)


def test_pod_affinity_always_rebuilds():
    """Affinity encodings re-count 'existing pods per domain' on every
    bind: a snapshot with any affinity term runs the full producer."""
    from kube_arbitrator_tpu.api.info import PodAffinityTerm

    sim = _mini_sim()
    j = sim.cluster.jobs["j1"]
    sim.add_task(
        j, 100, 1024**2,
        labels={"app": "web"},
        affinity=[PodAffinityTerm(match_labels=(("app", "web"),), anti=True)],
    )
    arena = SnapshotArena(sim, verify_every=0)
    arena.snapshot()
    snap = arena.snapshot()
    assert arena.last_rebuild_reason == "pod_affinity"
    assert_packs_identical(snap.tensors, build_snapshot(sim.cluster).tensors)


def test_set_drift_safety_net():
    """Even a direct dict mutation with NO emission at all is caught by
    the set-membership net before the delta path can serve a stale pack."""
    sim = _mini_sim()
    arena = SnapshotArena(sim, verify_every=0)
    arena.snapshot()
    from kube_arbitrator_tpu.api.info import QueueInfo

    sim.cluster.queues["rogue"] = QueueInfo(uid="rogue", name="rogue")
    snap = arena.snapshot()
    assert arena.last_rebuild_reason == "set_drift"
    assert_packs_identical(snap.tensors, build_snapshot(sim.cluster).tensors)


# ---------------------------------------------------------------------------
# epoch / PackMeta / device plane


def test_epoch_advances_only_on_change():
    sim = _mini_sim()
    arena = SnapshotArena(sim, verify_every=0)
    arena.snapshot()
    e0 = arena.epoch
    arena.snapshot()  # nothing changed
    assert arena.epoch == e0
    assert arena.pack_meta.changed_fields == ()
    b = feasible_bind(sim, random.Random(0))
    sim.apply_binds([b])
    arena.snapshot()
    assert arena.epoch == e0 + 1
    assert "task_status" in arena.pack_meta.changed_fields
    assert arena.pack_meta.base_key.endswith(f":{e0}")


def test_verify_every_1_does_not_recurse():
    """Regression: verify()'s drain guard re-entered snapshot() while the
    consumed dirty sets were still populated — verify_every=1 (a legal
    --arena-verify-every value) recursed unboundedly on the first delta."""
    sim = _mini_sim()
    arena = SnapshotArena(sim, verify_every=1)
    arena.snapshot()
    b = feasible_bind(sim, random.Random(3))
    sim.apply_binds([b])
    snap = arena.snapshot()  # delta + immediate epoch check
    assert arena.last_rebuild_reason is None
    assert_packs_identical(snap.tensors, build_snapshot(sim.cluster).tensors)


def test_static_rv_window_change_rides_changed_fields():
    """Regression: rv_window is a static (non-array) field that can move
    on a pure delta cycle; it must appear in PackMeta.changed_fields or
    the RPC delta path patches the rv_* arrays while the sidecar keeps a
    stale compile-time window."""
    sim = SimCluster()
    sim.add_queue("default")
    sim.add_node("n1", cpu_milli=200000, memory=400 * 1024**3, max_tasks=200)
    j = sim.add_job("j1", queue="default")
    for _ in range(40):
        sim.add_task(j, 100, 1024**2, status=TaskStatus.RUNNING, node="n1")
    arena = SnapshotArena(sim, verify_every=0)
    w0 = arena.snapshot().tensors.rv_window
    running = tasks_by_status(sim, TaskStatus.RUNNING)
    sim.apply_evicts([EvictIntent(t.uid) for t in running[:20]])
    snap = arena.snapshot()
    assert arena.last_rebuild_reason is None
    assert snap.tensors.rv_window != w0  # the bucket actually moved
    assert "rv_window" in arena.pack_meta.changed_fields
    assert_packs_identical(snap.tensors, build_snapshot(sim.cluster).tensors)
    # and the codec can ship it: statics round-trip as python scalars
    grpc_pb = pytest.importorskip("kube_arbitrator_tpu.rpc.decision_pb2")
    from kube_arbitrator_tpu.rpc.codec import pack_tensors, unpack_fields

    req = grpc_pb.SnapshotRequest()
    pack_tensors(snap.tensors, req.tensors, fields=arena.pack_meta.changed_fields)
    patch = unpack_fields(SnapshotTensors, req.tensors)
    assert patch["rv_window"] == snap.tensors.rv_window
    assert isinstance(patch["rv_window"], int)


def test_device_pack_reuse_and_delta():
    sim = generate_cluster(num_nodes=12, num_jobs=4, tasks_per_job=6,
                           num_queues=2, seed=4)
    arena = SnapshotArena(sim, verify_every=0)
    s0 = arena.snapshot()
    actions = ("allocate", "backfill")
    arena.device_pack(actions)
    assert arena._resident.last_mode == "full"
    full_bytes = arena._resident.last_upload_bytes
    arena.device_pack(actions)
    assert arena._resident.last_mode == "reuse"
    assert arena._resident.last_upload_bytes == 0
    b = feasible_bind(sim, random.Random(1))
    sim.apply_binds([b])
    s1 = arena.snapshot()
    st = arena.device_pack(actions)
    assert arena._resident.last_mode == "delta"
    assert 0 < arena._resident.last_upload_bytes < full_bytes
    # the resident view must equal the host pack byte for byte
    for f in dataclasses.fields(SnapshotTensors):
        if f.metadata.get("static"):
            continue
        np.testing.assert_array_equal(
            np.asarray(getattr(st, f.name)),
            np.asarray(getattr(s1.tensors, f.name)), err_msg=f.name,
        )


def test_scatter_row_padding_is_idempotent():
    """_pad_rows repeats the last (index, row) pair to reach a stable
    compile bucket; duplicate .at[i].set(v) with identical v must land
    the same result as the unpadded scatter."""
    buf = np.arange(40, dtype=np.float32).reshape(10, 4)
    rows = np.array([2, 7], dtype=np.int32)
    vals = np.full((2, 4), -1.0, dtype=np.float32)
    idx_p, vals_p = _pad_rows(rows, vals)
    assert len(idx_p) >= len(rows) and len(idx_p) == len(vals_p)
    out = np.asarray(_scatter_copy(buf.copy(), idx_p, vals_p))
    expect = buf.copy()
    expect[rows] = vals
    np.testing.assert_array_equal(out, expect)


def test_arena_decisions_match_full_rebuild_decisions():
    """End to end: identical packs -> bit-identical decisions."""
    from kube_arbitrator_tpu.framework import Scheduler

    def mk():
        return generate_cluster(num_nodes=16, num_jobs=6, tasks_per_job=8,
                                num_queues=2, seed=21, running_fraction=0.3)

    a = Scheduler(mk(), arena=True)
    a.arena.verify_every = 3
    b = Scheduler(mk())
    for cyc in range(6):
        ra, rb = a.run_once(), b.run_once()
        assert sorted((x.task_uid, x.node_name) for x in ra.binds) == \
            sorted((x.task_uid, x.node_name) for x in rb.binds), cyc
        assert sorted(x.task_uid for x in ra.evicts) == \
            sorted(x.task_uid for x in rb.evicts), cyc
    assert a.history[-1].upload_ms >= 0.0


# ---------------------------------------------------------------------------
# live-cache watch-plane deltas


def test_live_cache_emits_row_deltas():
    from kube_arbitrator_tpu.cache import FakeApiServer, LiveCache
    from test_live_cache import make_node, make_pod, make_podgroup

    api = FakeApiServer()
    live = LiveCache(api)
    for i in range(3):
        api.create("nodes", make_node(f"n{i}", cpu="8", memory="16Gi"))
    api.create("queues", {"metadata": {"name": "default"}, "spec": {"weight": 1}})
    api.create("podgroups", make_podgroup("g1", min_member=1, queue="default"))
    for i in range(4):
        api.create("pods", make_pod(f"p{i}", group="g1", cpu="500m", memory="256Mi"))
    live.sync()
    arena = SnapshotArena(live, verify_every=0)
    arena.snapshot()
    assert arena.last_rebuild_reason == "seed"
    # actuate a bind through the apiserver; the watch event is an
    # in-place pod update -> row delta, NOT a structural rebuild
    live.apply_binds([BindIntent(next(iter(live._pod_ref)), "n0")])
    live.sync()
    snap = arena.snapshot()
    assert arena.last_rebuild_reason is None
    assert_packs_identical(snap.tensors, build_snapshot(live.cluster).tensors)
    # a NEW pod arriving is structural
    api.create("pods", make_pod("p-late", group="g1", cpu="250m", memory="128Mi"))
    live.sync()
    snap = arena.snapshot()
    assert arena.last_rebuild_reason == "task_set"
    assert_packs_identical(snap.tensors, build_snapshot(live.cluster).tensors)


# ---------------------------------------------------------------------------
# RPC pack reuse (runs only when grpc is importable)


def test_rpc_delta_shipping_and_resend():
    pytest.importorskip("grpc")
    from kube_arbitrator_tpu.rpc import DecisionService, RemoteDecider, serve

    svc = DecisionService()
    server, port = serve("127.0.0.1:0", service=svc)
    try:
        from kube_arbitrator_tpu.framework import Scheduler

        def mk():
            return generate_cluster(num_nodes=12, num_jobs=4, tasks_per_job=6,
                                    num_queues=2, seed=13)

        remote = Scheduler(mk(), decider=RemoteDecider(f"127.0.0.1:{port}"),
                           arena=True)
        local = Scheduler(mk())
        for cyc in range(3):
            rr, rl = remote.run_once(), local.run_once()
            assert sorted((x.task_uid, x.node_name) for x in rr.binds) == \
                sorted((x.task_uid, x.node_name) for x in rl.binds), cyc
        # deltas actually rode the wire
        assert remote.decider._resident_key is not None
        # sidecar restart: wipe the resident pack -> FAILED_PRECONDITION
        # -> transparent full resend, decisions unaffected
        with svc._lock:
            svc._pack_key = svc._pack = None
        rr, rl = remote.run_once(), local.run_once()
        assert sorted((x.task_uid, x.node_name) for x in rr.binds) == \
            sorted((x.task_uid, x.node_name) for x in rl.binds)
        remote.decider.close()
    finally:
        server.stop(grace=None)
