"""Decision-plane RPC: codec roundtrip + scheduler against a live sidecar.

The sidecar runs in-process on a localhost ephemeral port (the gRPC server
thread pool stands in for the separate accelerator host); assertions are
bind-for-bind equality with the in-process path on the same cluster.
"""
import numpy as np
import pytest

pytest.importorskip("grpc")

from kube_arbitrator_tpu.cache import build_snapshot, generate_cluster
from kube_arbitrator_tpu.framework import Scheduler
from kube_arbitrator_tpu.framework.conf import SchedulerConfig, dump_conf, load_conf
from kube_arbitrator_tpu.rpc import DecisionService, RemoteDecider, serve
from kube_arbitrator_tpu.rpc.codec import pack_tensors, unpack_tensors
from kube_arbitrator_tpu.rpc import decision_pb2 as pb


@pytest.fixture(scope="module")
def sidecar():
    server, port = serve("127.0.0.1:0", service=DecisionService())
    yield f"127.0.0.1:{port}"
    server.stop(grace=None)


def test_codec_roundtrip():
    from kube_arbitrator_tpu.cache.snapshot import SnapshotTensors

    sim = generate_cluster(num_nodes=16, num_jobs=3, tasks_per_job=4, num_queues=2, seed=7)
    st = build_snapshot(sim.cluster).tensors
    req = pb.SnapshotRequest()
    pack_tensors(st, req.tensors)
    st2 = unpack_tensors(SnapshotTensors, pb.SnapshotRequest.FromString(req.SerializeToString()).tensors)
    for t in req.tensors:
        np.testing.assert_array_equal(
            np.asarray(getattr(st, t.name)), np.asarray(getattr(st2, t.name)), err_msg=t.name
        )


def test_conf_yaml_roundtrip():
    conf = """
actions: "allocate, preempt, reclaim, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
    disableJobOrder: true
- plugins:
  - name: drf
  - name: nodeorder
    arguments: {policy: binpack}
"""
    cfg = load_conf(conf)
    assert load_conf(dump_conf(cfg)) == cfg
    assert load_conf(dump_conf(SchedulerConfig.default())) == SchedulerConfig.default()


def test_health(sidecar):
    d = RemoteDecider(sidecar)
    h = d.health()
    assert h.device_count >= 1
    assert h.platform == "cpu"  # conftest forces the CPU platform
    d.close()


def test_remote_matches_local(sidecar):
    sim_l = generate_cluster(num_nodes=32, num_jobs=6, tasks_per_job=8, num_queues=2, seed=11)
    sim_r = generate_cluster(num_nodes=32, num_jobs=6, tasks_per_job=8, num_queues=2, seed=11)
    local = Scheduler(sim_l)
    remote = Scheduler(sim_r, decider=RemoteDecider(sidecar))
    local.run(max_cycles=3)
    remote.run(max_cycles=3)
    bound_l = {t.uid: t.node_name for j in sim_l.cluster.jobs.values() for t in j.tasks.values()}
    bound_r = {t.uid: t.node_name for j in sim_r.cluster.jobs.values() for t in j.tasks.values()}
    assert bound_l == bound_r
    assert sum(s.binds for s in remote.history) == sum(s.binds for s in local.history) > 0
    remote.decider.close()


def test_remote_full_actions_with_preemption(sidecar):
    """Preempt/reclaim decisions (evict intents) survive the wire too."""
    conf = load_conf(
        'actions: "allocate, preempt, reclaim, backfill"\n'
        "tiers:\n"
        "- plugins:\n"
        "  - name: priority\n"
        "  - name: gang\n"
        "- plugins:\n"
        "  - name: drf\n"
        "  - name: predicates\n"
        "  - name: proportion\n"
    )
    sim = generate_cluster(num_nodes=16, num_jobs=8, tasks_per_job=6, num_queues=3, seed=3)
    from kube_arbitrator_tpu.utils.audit import AuditLog

    audit = AuditLog(capacity=8)
    sched = Scheduler(sim, config=conf, decider=RemoteDecider(sidecar), audit=audit)
    sched.run(max_cycles=4)
    assert sum(s.binds for s in sched.history) > 0
    # the decision-audit aux crossed the RPC reply pack: remote cycles
    # assemble the same record shape local ones do (fairness ledger from
    # queue_deserved/queue_alloc, int-typed attribution arrays held to
    # the decode-side DECISIONS_SCHEMA twin)
    rec = audit.last()
    assert rec is not None and rec.fairness, "remote cycle missing ledger"
    assert len(audit.entries()) == len(sched.history)
    sched.decider.close()


# ---- retry backoff (chaos-plane satellite) ----


def test_backoff_is_capped_exponential_with_bounded_jitter():
    from kube_arbitrator_tpu.utils.backoff import backoff_delay_s

    base, cap = 1.0, 30.0
    for attempt in range(1, 12):
        raw = min(cap, base * 2 ** (attempt - 1))
        d = backoff_delay_s(attempt, base, cap, jitter_seed=0)
        assert raw * 0.5 <= d <= raw, (attempt, d)
    # capped: late attempts stop growing
    assert backoff_delay_s(20, base, cap) <= cap
    assert backoff_delay_s(0, base, cap) == 0.0


def test_backoff_jitter_is_deterministic_and_seed_keyed():
    from kube_arbitrator_tpu.utils.backoff import backoff_delay_s

    a = [backoff_delay_s(i, 1.0, 30.0, jitter_seed=7) for i in range(1, 6)]
    b = [backoff_delay_s(i, 1.0, 30.0, jitter_seed=7) for i in range(1, 6)]
    c = [backoff_delay_s(i, 1.0, 30.0, jitter_seed=8) for i in range(1, 6)]
    assert a == b
    assert a != c  # different clients de-synchronize


def test_remote_decider_retry_uses_injected_sleep_and_schedule():
    """Retries against a dead endpoint must sleep through the injected
    hook (never wall-clock) with exactly the deterministic backoff
    schedule."""
    import grpc

    from kube_arbitrator_tpu.cache import build_snapshot
    from kube_arbitrator_tpu.framework.conf import SchedulerConfig
    from kube_arbitrator_tpu.utils.backoff import backoff_delay_s

    slept = []
    d = RemoteDecider(
        "127.0.0.1:1",  # nothing listens: UNAVAILABLE
        timeout_s=5.0,
        retries=2,
        retry_backoff_s=0.25,
        retry_backoff_cap_s=2.0,
        jitter_seed=42,
        sleep_fn=slept.append,
    )
    sim = generate_cluster(num_nodes=8, num_jobs=2, tasks_per_job=3, num_queues=1, seed=0)
    st = build_snapshot(sim.cluster).tensors
    with pytest.raises(grpc.RpcError):
        d.decide(st, SchedulerConfig.default())
    assert slept == [
        backoff_delay_s(1, 0.25, 2.0, 42),
        backoff_delay_s(2, 0.25, 2.0, 42),
    ]
    d.close()


def test_sidecar_multi_tenant_pack_isolation(sidecar):
    """Fleet serving: two frontends with distinct tenant ids interleaved
    on ONE sidecar must keep independent delta streams — before the
    per-tenant resident packs they evicted each other back to a full
    resend every cycle."""
    from kube_arbitrator_tpu.utils.metrics import metrics

    sims = [
        generate_cluster(num_nodes=16, num_jobs=5, tasks_per_job=4,
                         num_queues=2, seed=31 + i, running_fraction=0.2)
        for i in range(2)
    ]
    scheds = [
        Scheduler(s, decider=RemoteDecider(sidecar, tenant=f"iso-t{i}"), arena=True)
        for i, s in enumerate(sims)
    ]
    resend0 = metrics().counter_value("rpc_pack_resend_total")
    reuse0 = metrics().counter_value("rpc_pack_reuse_total")
    try:
        for _cycle in range(3):
            for s in scheds:
                s.run(max_cycles=1, until_idle=False)
    finally:
        for s in scheds:
            s.decider.close()
    assert metrics().counter_value("rpc_pack_resend_total") == resend0, (
        "interleaved tenants evicted each other's resident packs"
    )
    # both tenants' cycles 2..3 patched their own resident pack
    assert metrics().counter_value("rpc_pack_reuse_total") - reuse0 >= 4


def test_pipelined_full_resend_after_sidecar_restart(sidecar):
    """The FAILED_PRECONDITION full-resend path under the PIPELINED
    RemoteDecider (only the sequential path was covered): the sidecar
    restarts (resident packs dropped) while a delta decide is in flight
    on the executor's worker; the frontend must transparently re-send
    the pack in full and the run must place exactly what a
    never-restarted run places."""
    from kube_arbitrator_tpu.pipeline import PipelinedExecutor
    from kube_arbitrator_tpu.rpc.sidecar import DecisionService
    from kube_arbitrator_tpu.utils.metrics import metrics

    # a dedicated sidecar so drop_resident_packs cannot race the
    # module-scoped fixture's other tests
    svc = DecisionService()
    server, port = serve("127.0.0.1:0", service=svc)
    target = f"127.0.0.1:{port}"
    mk = lambda: generate_cluster(  # noqa: E731
        num_nodes=24, num_jobs=5, tasks_per_job=6, num_queues=2, seed=47,
        running_fraction=0.2,
    )
    sim_r, sim_ref = mk(), mk()
    sched = Scheduler(sim_r, decider=RemoteDecider(target, tenant="pipe"), arena=True)
    executor = PipelinedExecutor(
        sched,
        # the restart lands THROUGH the mid-flight seam: ingest_fn runs
        # on the main thread while the worker's decide (carrying a delta
        # keyed to the now-dropped base) is in flight
        ingest_fn=lambda: (svc.drop_resident_packs(), 0)[1],
    )
    resend0 = metrics().counter_value("rpc_pack_resend_total")
    try:
        for _ in range(4):
            executor.step()
    finally:
        executor.close()
        sched.decider.close()
        server.stop(grace=None)
    ref = Scheduler(sim_ref, arena=True)
    ref.run(max_cycles=4, until_idle=False)
    bound_r = {t.uid: t.node_name for j in sim_r.cluster.jobs.values() for t in j.tasks.values()}
    bound_ref = {t.uid: t.node_name for j in sim_ref.cluster.jobs.values() for t in j.tasks.values()}
    assert bound_r == bound_ref, "restart under pipelining changed decisions"
    assert metrics().counter_value("rpc_pack_resend_total") > resend0, (
        "the full-resend path never fired"
    )


def test_pipelined_remote_matches_sequential_remote(sidecar):
    """Overlap through the wire: run_pipelined with a RemoteDecider (the
    epoch-keyed delta protocol under the frozen-pack discipline) places
    exactly what the sequential remote loop places."""
    sim_a = generate_cluster(num_nodes=24, num_jobs=5, tasks_per_job=6, num_queues=2, seed=17)
    sim_b = generate_cluster(num_nodes=24, num_jobs=5, tasks_per_job=6, num_queues=2, seed=17)
    seq = Scheduler(sim_a, decider=RemoteDecider(sidecar), arena=True)
    pipe = Scheduler(sim_b, decider=RemoteDecider(sidecar), arena=True)
    try:
        seq.run(max_cycles=4)
        pipe.run_pipelined(max_cycles=4)
    finally:
        seq.decider.close()
        pipe.decider.close()
    bound_a = {t.uid: t.node_name for j in sim_a.cluster.jobs.values() for t in j.tasks.values()}
    bound_b = {t.uid: t.node_name for j in sim_b.cluster.jobs.values() for t in j.tasks.values()}
    assert bound_a == bound_b
    assert sum(s.binds for s in seq.history) == sum(s.binds for s in pipe.history) > 0
