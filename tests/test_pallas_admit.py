"""Pallas admission kernel vs its jnp reference (interpret mode on CPU).

The kernel itself runs on TPU in production (opt-in); here interpret=True
executes the same kernel body under the Pallas interpreter so the logic —
including the exact-int32 MXU prefix-sum construction — stays verified on
every platform.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from kube_arbitrator_tpu.ops.pallas_admit import (
    admit_reference,
    pallas_admit,
    pallas_admit_eligible,
)


def make_case(seed, best_effort=False, ports=False, n=384):
    r = np.random.default_rng(seed)
    req = (
        np.zeros(3, np.float32)
        if best_effort
        else np.array([1000.0, 2048.0, 0.0], np.float32)
    )
    return (
        jnp.asarray(req),
        jnp.int32(int(r.integers(1, 500))),
        jnp.asarray(np.array([1, 0], np.int32) if ports else np.zeros(2, np.int32)),
        jnp.asarray(bool(ports)),
        jnp.asarray((r.random((3, n)) * 32000).astype(np.float32)),
        jnp.asarray((r.random((3, n)) * 8000).astype(np.float32)),
        jnp.asarray(r.integers(0, 4, (2, n)).astype(np.int32)),
        jnp.asarray(r.integers(0, 100, (1, n)).astype(np.int32)),
        jnp.asarray(np.full((1, n), 110, np.int32)),
        jnp.asarray((r.random((1, n)) > 0.2).astype(np.int32)),
    )


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("best_effort", [False, True])
@pytest.mark.parametrize("ports", [False, True])
def test_kernel_matches_reference(seed, best_effort, ports):
    args = make_case(seed, best_effort, ports)
    got = pallas_admit(*args, best_effort=best_effort, interpret=True)
    want = admit_reference(*args, best_effort=best_effort)
    for i, (g, w) in enumerate(zip(got, want)):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w), err_msg=f"output {i}")


def test_releasing_fallback():
    """Zero idle capacity everywhere -> the kernel pivots to releasing
    space and reports use_rel."""
    args = list(make_case(5))
    args[4] = jnp.zeros_like(args[4])  # idle = 0
    p, total, use_rel, idle2, rel2, _, _ = pallas_admit(*args, interpret=True)
    assert bool(use_rel) and int(total) > 0
    np.testing.assert_array_equal(np.asarray(idle2), 0.0)
    assert float(np.asarray(rel2).sum()) < float(np.asarray(args[5]).sum())


def test_exact_cumsum_large_values():
    """Counts > 256 exercise the hi/lo byte split (a single bf16 MXU pass
    would drift); totals must be bit-exact."""
    n = 256
    args = list(make_case(7, n=n))
    args[1] = jnp.int32(4096)  # budget
    args[4] = jnp.asarray(np.full((3, n), 3.0e7, np.float32))  # idle >> req
    args[8] = jnp.asarray(np.full((1, n), 4096, np.int32))  # max_tasks
    args[7] = jnp.zeros((1, n), jnp.int32)
    got = pallas_admit(*args, interpret=True)
    want = admit_reference(*args)
    np.testing.assert_array_equal(np.asarray(got[0]), np.asarray(want[0]))
    assert int(got[1]) == int(want[1]) == 4096


def test_eligibility():
    assert pallas_admit_eligible(10112)
    assert pallas_admit_eligible(16384)
    assert not pallas_admit_eligible(16512)
    assert not pallas_admit_eligible(100)
