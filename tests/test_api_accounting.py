"""Data-model accounting tests.

Ports the intent of the reference's ``api/job_info_test.go`` /
``api/node_info_test.go``: Add/Remove task arithmetic on Idle/Used/
Releasing, epsilon comparison behavior, and gang readiness counting.
"""
import numpy as np
import pytest

from kube_arbitrator_tpu.api import TaskStatus, resource as res
from kube_arbitrator_tpu.cache import SimCluster


def test_epsilon_less_equal():
    a = res.make(1000, 1024**3, 0)
    assert res.less_equal(a, a)  # equal fits (within epsilon)
    # 9 milli-cpu over: still fits (eps = 10 milli)
    assert res.less_equal(res.make(1009, 1024**3, 0), a)
    # 11 milli-cpu over: does not fit
    assert not res.less_equal(res.make(1011, 1024**3, 0), a)
    # 9 MiB of memory over: fits
    assert res.less_equal(res.make(1000, 1024**3 + 9 * 1024**2, 0), a)
    assert not res.less_equal(res.make(1000, 1024**3 + 11 * 1024**2, 0), a)


def test_is_empty_epsilon():
    assert res.is_empty(res.make(9, 9 * 1024**2, 9))
    assert not res.is_empty(res.make(11, 0, 0))


def test_sub_checked_panics_like_reference():
    with pytest.raises(ValueError):
        res.sub_checked(res.make(100, 0, 0), res.make(200, 0, 0))


def test_node_add_remove_task_accounting():
    sim = SimCluster()
    n = sim.add_node("n1", cpu_milli=8000, memory=16 * 1024**3)
    q = sim.add_queue("default")
    j = sim.add_job("j1")
    t = sim.add_task(j, 2000, 4 * 1024**3, status=TaskStatus.RUNNING, node="n1")
    np.testing.assert_allclose(n.idle, res.make(6000, 12 * 1024**3, 0, 40))
    np.testing.assert_allclose(n.used, res.make(2000, 4 * 1024**3, 0))
    n.remove_task(t)
    np.testing.assert_allclose(n.idle, res.make(8000, 16 * 1024**3, 0, 40))
    np.testing.assert_allclose(n.used, res.zeros())


def test_node_releasing_accounting():
    """Releasing tasks subtract idle AND count releasing; pipelined tasks
    consume releasing (node_info.go:101-127)."""
    sim = SimCluster()
    n = sim.add_node("n1", cpu_milli=8000, memory=16 * 1024**3)
    j = sim.add_job("j1")
    t = sim.add_task(j, 2000, 4 * 1024**3, status=TaskStatus.RELEASING, node="n1")
    np.testing.assert_allclose(n.releasing, res.make(2000, 4 * 1024**3, 0))
    np.testing.assert_allclose(n.idle, res.make(6000, 12 * 1024**3, 0, 40))
    # a pipelined task consumes the releasing budget
    t2 = sim.add_task(j, 2000, 4 * 1024**3, status=TaskStatus.PIPELINED, node="n1")
    np.testing.assert_allclose(n.releasing, res.zeros())


def test_node_oversubscription_raises():
    sim = SimCluster()
    sim.add_node("n1", cpu_milli=1000, memory=1024**3)
    j = sim.add_job("j1")
    with pytest.raises(ValueError):
        sim.add_task(j, 2000, 0, status=TaskStatus.RUNNING, node="n1")


def test_gang_ready_and_valid_counts():
    sim = SimCluster()
    sim.add_node("n1", cpu_milli=8000, memory=16 * 1024**3)
    j = sim.add_job("j1", min_available=3)
    sim.add_task(j, 1000, 1024**3)  # pending: valid, not ready
    sim.add_task(j, 1000, 1024**3, status=TaskStatus.RUNNING, node="n1")
    sim.add_task(j, 1000, 1024**3, status=TaskStatus.SUCCEEDED)
    assert j.ready_task_num() == 2
    assert j.valid_task_num() == 3
    assert not j.is_ready()
    assert j.is_valid()


def test_dominant_share():
    total = res.make(10000, 100 * 1024**3, 10000)
    alloc = res.make(1000, 50 * 1024**3, 0)
    assert res.dominant_share(alloc, total) == pytest.approx(0.5)
    # zero-total resource: share = 1 if allocated (helpers.go:38-48)
    total0 = res.make(10000, 100 * 1024**3, 0)
    assert res.dominant_share(res.make(0, 0, 1), total0) == 1.0
