"""Live-cluster plane integration: fake apiserver -> list/watch ingestion
-> scheduling -> bind/evict/status actuation -> watch round-trip.

The live analog of the reference's informer + default-backend stack
(cache.go:225-306, :88-165); scenarios mirror what its cache unit tests
(cache_test.go TestAddPod/TestAddNode) and the e2e bind flow exercise.
"""
import numpy as np
import pytest

from kube_arbitrator_tpu.api import TaskStatus
from kube_arbitrator_tpu.api import resource as res
from kube_arbitrator_tpu.cache import FakeApiServer, LiveCache
from kube_arbitrator_tpu.framework import Scheduler
from kube_arbitrator_tpu.options import options, reset_options

GB = 1024**3


@pytest.fixture(autouse=True)
def _fresh_options():
    reset_options()
    yield
    reset_options()


def make_pod(name, ns="default", group=None, cpu="1", memory="1Gi",
             scheduler="kube-batch", node="", phase="Pending", uid=None,
             priority=1):
    pod = {
        "metadata": {
            "name": name,
            "namespace": ns,
            "uid": uid or f"uid-{ns}-{name}",
            "annotations": {},
            "labels": {},
        },
        "spec": {
            "schedulerName": scheduler,
            "nodeName": node,
            "priority": priority,
            "containers": [
                {"resources": {"requests": {"cpu": cpu, "memory": memory}}}
            ],
        },
        "status": {"phase": phase},
    }
    if group:
        pod["metadata"]["annotations"]["scheduling.k8s.io/group-name"] = group
    return pod


def make_node(name, cpu="4", memory="8Gi", pods=110):
    return {
        "metadata": {"name": name, "labels": {}},
        "status": {"allocatable": {"cpu": cpu, "memory": memory, "pods": pods}},
        "spec": {},
    }


def make_podgroup(name, ns="default", min_member=1, queue=""):
    pg = {
        "metadata": {"name": name, "namespace": ns, "creationTimestamp": 1.0},
        "spec": {"minMember": min_member},
        "status": {},
    }
    if queue:
        pg["spec"]["queue"] = queue
    return pg


def seed_gang_cluster(api, n_nodes=2, n_pods=3, min_member=3):
    for i in range(n_nodes):
        api.create("nodes", make_node(f"n{i}"))
    api.create("queues", {"metadata": {"name": "default"}, "spec": {"weight": 1}})
    api.create("podgroups", make_podgroup("pg1", min_member=min_member))
    for i in range(n_pods):
        api.create("pods", make_pod(f"p{i}", group="pg1"))


def test_list_watch_sync_builds_model():
    api = FakeApiServer()
    seed_gang_cluster(api)
    # an assigned pod of another scheduler -> Others (cache.go:254-272)
    api.create("pods", make_pod("alien", scheduler="default-scheduler",
                                node="n0", phase="Running", cpu="2"))
    live = LiveCache(api)
    live.sync()

    assert set(live.cluster.nodes) == {"n0", "n1"}
    # attach axis defaults to 40 when the kubelet publishes no
    # attachable-volumes-* allocatable key (sim parity)
    assert np.allclose(live.cluster.nodes["n0"].allocatable, res.make(4000, 8 * GB, 0, 40))
    assert "default" in live.cluster.queues
    job = live.cluster.jobs["default/pg1"]
    assert job.min_available == 3 and len(job.tasks) == 3
    t = next(iter(job.tasks.values()))
    assert np.allclose(t.resreq, res.make(1000, GB))
    assert len(live.cluster.others) == 1
    # the alien pod consumes node capacity
    assert np.allclose(live.cluster.nodes["n0"].idle, res.make(2000, 7 * GB, 0, 40))


def test_scheduler_binds_through_adapter_and_watch_roundtrip():
    # 4 pods over minMember=3: jobStatus's strict '>' (session.go:159-197)
    # needs allocated > minMember for phase Running
    api = FakeApiServer()
    seed_gang_cluster(api, n_pods=4)
    live = LiveCache(api)
    sched = Scheduler(live)

    result = sched.run_once()
    assert len(result.binds) == 4
    # binds were POSTed: apiserver pods carry nodeName + kubelet emulation
    for i in range(4):
        pod = api.get("pods", "default", f"p{i}")
        assert pod["spec"]["nodeName"] in ("n0", "n1")
        assert pod["status"]["phase"] == "Running"
    # status write-back round-trips (PUT /status)
    pg = api.get("podgroups", "default", "pg1")
    assert pg["status"]["phase"] == "Running"

    # next pump: the MODIFIED watch events update the model
    live.sync()
    job = live.cluster.jobs["default/pg1"]
    assert all(t.status == TaskStatus.RUNNING for t in job.tasks.values())
    # node accounting reflects the running pods
    used = sum(np.asarray(n.used) for n in live.cluster.nodes.values())
    assert np.allclose(used, res.make(4000, 4 * GB))
    # second cycle: nothing pending, no new binds
    result2 = sched.run_once()
    assert result2.binds == []


def test_bind_failure_diverts_to_resync():
    api = FakeApiServer()
    seed_gang_cluster(api, min_member=1, n_pods=2)
    api.fail_bind_uids = {"uid-default-p0"}
    live = LiveCache(api)
    sched = Scheduler(live)

    sched.run_once()
    # p1 bound; p0's POST failed -> resync FIFO + FailedScheduling event
    assert api.get("pods", "default", "p1")["spec"]["nodeName"]
    assert not api.get("pods", "default", "p0")["spec"]["nodeName"]
    assert any(e.kind == "FailedScheduling" for e in live.events)

    # failure clears; resync re-GETs, the next cycle binds p0
    api.fail_bind_uids = set()
    sched.run_once()
    assert api.get("pods", "default", "p0")["spec"]["nodeName"]


def test_evict_deletes_pod_via_apiserver():
    api = FakeApiServer()
    api.create("nodes", make_node("n0", cpu="4"))
    api.create("queues", {"metadata": {"name": "qa"}, "spec": {"weight": 1}})
    api.create("queues", {"metadata": {"name": "qb"}, "spec": {"weight": 1}})
    api.create("podgroups", make_podgroup("victims", min_member=0, queue="qa"))
    api.create("podgroups", make_podgroup("claimer", min_member=1, queue="qb"))
    # queue A fills the node; queue B reclaims
    for i in range(4):
        api.create("pods", make_pod(f"v{i}", group="victims", cpu="1",
                                    memory="256Mi", node="n0", phase="Running"))
    api.create("pods", make_pod("c0", group="claimer", cpu="1", memory="256Mi"))
    live = LiveCache(api)
    from kube_arbitrator_tpu.framework.conf import load_conf

    # full-action conf WITH tiers: a tierless conf faithfully means no
    # plugins, hence no Reclaimable verdicts at all (util.go:30-64)
    cfg = load_conf(
        'actions: "reclaim, allocate, backfill, preempt"\n'
        "tiers:\n"
        "- plugins:\n"
        "  - name: priority\n"
        "  - name: gang\n"
        "- plugins:\n"
        "  - name: drf\n"
        "  - name: predicates\n"
        "  - name: proportion\n"
    )
    sched = Scheduler(live, config=cfg)
    result = sched.run_once()
    assert len(result.evicts) >= 1
    # DELETE hit the apiserver
    gone = [f"v{i}" for i in range(4) if api.get("pods", "default", f"v{i}") is None]
    assert len(gone) == len(result.evicts)
    # the deletion flows back through the watch into the model
    live.sync()
    vic_job = live.cluster.jobs["default/victims"]
    assert len(vic_job.tasks) == 4 - len(gone)


def test_recorded_watch_stream_replay(tmp_path):
    """VERDICT round-2 #3 'done' criterion: replay a recorded
    pod/node/PodGroup watch stream, schedule through the adapter, and
    round-trip the status write-back."""
    api = FakeApiServer()
    seed_gang_cluster(api, n_pods=4)
    path = str(tmp_path / "stream.jsonl")
    api.dump_stream(path)

    replayed = FakeApiServer.from_stream(FakeApiServer.load_stream(path))
    live = LiveCache(replayed)
    sched = Scheduler(live)
    result = sched.run_once()
    assert len(result.binds) == 4
    assert replayed.get("podgroups", "default", "pg1")["status"]["phase"] == "Running"


def test_pod_deletion_and_node_update_flow():
    api = FakeApiServer()
    seed_gang_cluster(api, min_member=1, n_pods=2)
    live = LiveCache(api)
    live.sync()
    assert len(live.cluster.jobs["default/pg1"].tasks) == 2

    api.delete("pods", "default", "p1")
    node = api.get("nodes", "", "n0")
    node["spec"]["unschedulable"] = True
    api.update("nodes", node)
    live.sync()
    assert len(live.cluster.jobs["default/pg1"].tasks) == 1
    assert live.cluster.nodes["n0"].unschedulable


def test_multi_term_node_affinity_translated_and_ored():
    """helpers.go:303-315: ALL nodeSelectorTerms are kept and ORed — a
    2-term pod schedules onto a node satisfying only the SECOND term
    (round-3 verdict missing #2: terms[0]-only over-constrained this)."""
    api = FakeApiServer()
    api.create("nodes", {**make_node("west-hdd"),
                         "metadata": {"name": "west-hdd",
                                      "labels": {"zone": "west", "disk": "hdd"}}})
    api.create("nodes", {**make_node("east"),
                         "metadata": {"name": "east", "labels": {"zone": "east"}}})
    api.create("queues", {"metadata": {"name": "default"}, "spec": {"weight": 1}})
    api.create("podgroups", make_podgroup("pg1", min_member=1))
    pod = make_pod("p0", group="pg1")
    pod["spec"]["affinity"] = {
        "nodeAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": {
                "nodeSelectorTerms": [
                    {"matchExpressions": [
                        {"key": "zone", "operator": "In", "values": ["west"]},
                        {"key": "disk", "operator": "In", "values": ["ssd"]},
                    ]},
                    {"matchExpressions": [
                        {"key": "zone", "operator": "In", "values": ["east"]},
                    ]},
                ]
            }
        }
    }
    api.create("pods", pod)
    live = LiveCache(api)
    live.sync()
    t = next(iter(live.cluster.jobs["default/pg1"].tasks.values()))
    assert len(t.node_affinity) == 2  # both terms survive translation

    sched = Scheduler(live)
    result = sched.run_once()
    assert len(result.binds) == 1
    # west-hdd fails term 1 (disk!=ssd) and term 2 (zone!=east); east
    # passes term 2 — OR semantics place the pod there
    assert api.get("pods", "default", "p0")["spec"]["nodeName"] == "east"


def test_pod_affinity_json_translated():
    """predicates.go:186-198: required pod (anti-)affinity JSON lands in
    TaskInfo.affinity_terms and steers live scheduling (anti-affinity on
    hostname forces the two pods apart)."""
    api = FakeApiServer()
    for i in range(2):
        api.create("nodes", make_node(f"n{i}"))
    api.create("queues", {"metadata": {"name": "default"}, "spec": {"weight": 1}})
    api.create("podgroups", make_podgroup("pg1", min_member=2))
    for i in range(2):
        pod = make_pod(f"p{i}", group="pg1")
        pod["metadata"]["labels"] = {"app": "db"}
        pod["spec"]["affinity"] = {
            "podAntiAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [
                    {"labelSelector": {"matchLabels": {"app": "db"}},
                     "topologyKey": "kubernetes.io/hostname"}
                ]
            }
        }
        api.create("pods", pod)
    live = LiveCache(api)
    live.sync()
    t = next(iter(live.cluster.jobs["default/pg1"].tasks.values()))
    assert len(t.affinity_terms) == 1
    term = t.affinity_terms[0]
    assert term.anti and term.match_labels == (("app", "db"),)
    assert term.topology_key == "kubernetes.io/hostname"

    sched = Scheduler(live)
    result = sched.run_once()
    assert len(result.binds) == 2
    nodes = {api.get("pods", "default", f"p{i}")["spec"]["nodeName"] for i in range(2)}
    assert nodes == {"n0", "n1"}  # anti-affinity forced them apart


def test_namespace_as_queue_backend():
    from kube_arbitrator_tpu.options import ServerOptions, set_options

    set_options(ServerOptions(namespace_as_queue=True))
    api = FakeApiServer()
    api.create("namespaces", {"metadata": {"name": "team-a"}})
    api.create("nodes", make_node("n0"))
    api.create("pods", make_pod("p0", ns="team-a", group="g", cpu="1"))
    api.create("podgroups", make_podgroup("g", ns="team-a", min_member=1))
    live = LiveCache(api)
    live.sync()
    assert "team-a" in live.cluster.queues
    assert live.cluster.jobs["team-a/g"].queue_uid == "team-a"


def test_cli_watch_stream_mode(tmp_path, capsys):
    """The binary surface reaches the live plane: --watch-stream replays a
    recorded apiserver stream, schedules through LiveCache, and actuates
    back into the replayed server."""
    api = FakeApiServer()
    seed_gang_cluster(api, n_pods=4)
    path = str(tmp_path / "stream.jsonl")
    api.dump_stream(path)

    from kube_arbitrator_tpu.cli import main

    rc = main(["--watch-stream", path, "--cycles", "3", "--json"])
    assert rc == 0
    out = capsys.readouterr().out
    import json

    lines = [json.loads(l) for l in out.strip().splitlines() if l.startswith("{")]
    assert sum(l["binds"] for l in lines) == 4


def test_pv_zone_ignores_non_in_operators():
    """A NotIn/Gt zone term is an exclusion, not a pin — misreading it
    would pin the pod to exactly the zone the PV excludes."""
    from kube_arbitrator_tpu.cache.live import pv_zone

    pv = {"metadata": {"name": "pv1"},
          "spec": {"nodeAffinity": {"required": {"nodeSelectorTerms": [
              {"matchExpressions": [
                  {"key": "topology.kubernetes.io/zone",
                   "operator": "NotIn", "values": ["zone-a"]}]}]}}}}
    assert pv_zone(pv) == ""
    pv["spec"]["nodeAffinity"]["required"]["nodeSelectorTerms"][0][
        "matchExpressions"][0]["operator"] = "In"
    assert pv_zone(pv) == "zone-a"


def test_conflicting_pv_zones_make_pod_unschedulable():
    """Two PVCs bound to PVs in different zones: no node can attach both —
    the pod must stay pending (VolumeZone-predicate behavior), not bind to
    the first zone."""
    from kube_arbitrator_tpu.cache import FakeApiServer, LiveCache
    from kube_arbitrator_tpu.framework import Scheduler

    api = FakeApiServer()
    for zone, n in (("zone-a", "n0"), ("zone-b", "n1")):
        node = make_node(n)
        node["metadata"]["labels"]["topology.kubernetes.io/zone"] = zone
        api.create("nodes", node)
    api.create("queues", {"metadata": {"name": "default"}, "spec": {"weight": 1}})
    for zone, pv, claim in (("zone-a", "pva", "ca"), ("zone-b", "pvb", "cb")):
        api.create("persistentvolumes", {
            "metadata": {"name": pv,
                         "labels": {"topology.kubernetes.io/zone": zone}},
            "spec": {}})
        api.create("persistentvolumeclaims", {
            "metadata": {"namespace": "default", "name": claim},
            "spec": {"volumeName": pv}})
    api.create("podgroups", make_podgroup("pg1", min_member=1))
    pod = make_pod("p0", group="pg1")
    pod["spec"]["volumes"] = [
        {"name": "va", "persistentVolumeClaim": {"claimName": "ca"}},
        {"name": "vb", "persistentVolumeClaim": {"claimName": "cb"}},
    ]
    api.create("pods", pod)
    live = LiveCache(api)
    sched = Scheduler(live)
    result = sched.run_once()
    assert result.binds == []
    assert not api.get("pods", "default", "p0")["spec"]["nodeName"]
    assert any(e.reason == "VolumeZoneConflict" for e in live.events)
