"""Pipelined cycle plane: equivalence, revalidation, seams, chaos.

The decision-equivalence soak is the plane's acceptance bar: on a
quiescent delta stream a pipelined run must produce bit-identical
bind/evict streams to a sequential one, cycle for cycle — overlap buys
cadence, never different decisions.  The revalidation suite drives every
discard reason through the commit gate; the executor tests exercise the
mid-window churn path (the crash a naive pipelined commit would hit),
backpressure, and the journal tee; the chaos test proves the core
invariants hold when faults land inside the speculation window.
"""
import random
import threading
import time

import numpy as np
import pytest

from kube_arbitrator_tpu.api.types import TaskStatus
from kube_arbitrator_tpu.cache.sim import BindIntent, EvictIntent, generate_cluster
from kube_arbitrator_tpu.framework import Scheduler
from kube_arbitrator_tpu.framework.conf import load_conf
from kube_arbitrator_tpu.framework.session import Session, default_decider
from kube_arbitrator_tpu.options import reset_options
from kube_arbitrator_tpu.pipeline import (
    DeltaJournal,
    PipelinedExecutor,
    revalidate_decisions,
)
from kube_arbitrator_tpu.utils.metrics import metrics

FULL_CONF = (
    'actions: "reclaim, allocate, backfill, preempt"\n'
    "tiers:\n"
    "- plugins:\n"
    "  - name: priority\n"
    "  - name: gang\n"
    "- plugins:\n"
    "  - name: drf\n"
    "  - name: predicates\n"
    "  - name: proportion\n"
)


@pytest.fixture(autouse=True)
def _fresh():
    reset_options()
    metrics().reset()
    yield
    reset_options()
    metrics().reset()


def _mk(seed=7, running=0.4, nodes=12, jobs=8, tpj=5):
    return generate_cluster(
        num_nodes=nodes, num_jobs=jobs, tasks_per_job=tpj,
        num_queues=3, seed=seed, running_fraction=running,
    )


# ---------------------------------------------------------------------------
# decision equivalence


def test_quiescent_equivalence_soak():
    """Sequential vs pipelined on identical worlds with no external
    churn: every cycle's bind/evict stream must match exactly (the
    speculation window is empty, so the gate passes everything and the
    frozen epochs see exactly the states the sequential loop sees)."""
    seq = Scheduler(_mk(), config=load_conf(FULL_CONF), arena=True)
    pipe = Scheduler(_mk(), config=load_conf(FULL_CONF), arena=True)
    ex = PipelinedExecutor(pipe)
    try:
        for cycle in range(15):
            r = seq.run_once()
            out = ex.step()
            assert sorted((b.task_uid, b.node_name) for b in r.binds) == \
                sorted((b.task_uid, b.node_name) for b in out.binds), cycle
            assert sorted(e.task_uid for e in r.evicts) == \
                sorted(e.task_uid for e in out.evicts), cycle
            assert not out.discards, cycle
    finally:
        ex.close()
    assert len(pipe.history) == 15


def test_run_pipelined_until_idle_matches_sequential_totals():
    seq = Scheduler(_mk(seed=11, running=0.0), arena=True)
    pipe = Scheduler(_mk(seed=11, running=0.0), arena=True)
    n_seq = seq.run(max_cycles=6)
    n_pipe = pipe.run_pipelined(max_cycles=6)
    assert n_seq == n_pipe
    assert sum(s.binds for s in seq.history) == sum(s.binds for s in pipe.history)


# ---------------------------------------------------------------------------
# the revalidation gate


def _gate_world():
    sim = _mk(seed=3, running=0.5, nodes=4, jobs=3, tpj=4)
    index = {
        uid: t for j in sim.cluster.jobs.values() for uid, t in j.tasks.items()
    }
    pending = [t for t in index.values() if t.status == TaskStatus.PENDING]
    running = [t for t in index.values() if t.status == TaskStatus.RUNNING]
    assert pending and running
    return sim, pending, running


def test_gate_empty_journal_is_a_no_op():
    sim, pending, running = _gate_world()
    binds = [BindIntent(task_uid=pending[0].uid, node_name="node-00000")]
    evicts = [EvictIntent(task_uid=running[0].uid)]
    kept_b, kept_e, discards = revalidate_decisions(
        sim.cluster, binds, evicts, DeltaJournal()
    )
    assert kept_b == binds and kept_e == evicts and not discards


def test_gate_task_gone():
    sim, pending, _ = _gate_world()
    victim = pending[0]
    j = DeltaJournal()
    j.task_dirty(victim.uid)
    sim.cluster.jobs[victim.job_uid].tasks.pop(victim.uid)
    kept_b, _, discards = revalidate_decisions(
        sim.cluster,
        [BindIntent(task_uid=victim.uid, node_name="node-00000")], [], j,
    )
    assert not kept_b
    assert [d.reason for d in discards] == ["task_gone"]


def test_gate_already_bound():
    sim, pending, _ = _gate_world()
    t = pending[0]
    t.status = TaskStatus.BOUND
    t.node_name = "node-00001"
    j = DeltaJournal()
    j.task_dirty(t.uid)
    kept_b, _, discards = revalidate_decisions(
        sim.cluster, [BindIntent(task_uid=t.uid, node_name="node-00000")], [], j,
    )
    assert not kept_b and discards[0].reason == "already_bound"


def test_gate_node_gone_and_unsched():
    sim, pending, _ = _gate_world()
    a, b = pending[0], pending[1]
    sim.cluster.nodes.pop("node-00000")
    sim.cluster.nodes["node-00001"].unschedulable = True
    j = DeltaJournal()
    j.node_dirty("node-00000")
    j.node_dirty("node-00001")
    kept_b, _, discards = revalidate_decisions(
        sim.cluster,
        [
            BindIntent(task_uid=a.uid, node_name="node-00000"),
            BindIntent(task_uid=b.uid, node_name="node-00001"),
        ],
        [], j,
    )
    assert not kept_b
    assert sorted(d.reason for d in discards) == ["node_gone", "node_unsched"]


def test_gate_capacity_shrunk_counts_accepted_binds():
    """Two binds onto one shrunken node: headroom for one — the second
    must see the first's tentative usage and discard."""
    sim, pending, _ = _gate_world()
    # same-job tasks share one request profile, so 1.5x one request is
    # headroom for exactly one of the two
    by_job = {}
    for t in pending:
        by_job.setdefault(t.job_uid, []).append(t)
    a, b = next(ts for ts in by_job.values() if len(ts) >= 2)[:2]
    node = sim.cluster.nodes["node-00002"]
    node.idle = np.asarray(a.resreq) * 1.5
    node.releasing = np.zeros_like(node.idle)
    j = DeltaJournal()
    j.node_dirty(node.name)
    kept_b, _, discards = revalidate_decisions(
        sim.cluster,
        [
            BindIntent(task_uid=a.uid, node_name=node.name),
            BindIntent(task_uid=b.uid, node_name=node.name),
        ],
        [], j,
    )
    assert len(kept_b) == 1 and kept_b[0].task_uid == a.uid
    assert discards[0].reason == "capacity_shrunk"


def test_gate_not_evictable_and_structural_checks_everything():
    sim, pending, running = _gate_world()
    v = running[0]
    v.status = TaskStatus.RELEASING
    j = DeltaJournal()
    j.structural_event("relist")  # no per-row dirt: the structural flip
    _, kept_e, discards = revalidate_decisions(
        sim.cluster, [], [EvictIntent(task_uid=v.uid)], j,
    )
    assert not kept_e and discards[0].reason == "not_evictable"


# ---------------------------------------------------------------------------
# the executor: mid-window churn, journal, backpressure


def test_mid_window_task_delete_discards_instead_of_crashing():
    """A pod deleted while its bind decision is in flight: the sequential
    actuation path would KeyError; the gate drops the bind with
    ``task_gone`` and the loop keeps going."""
    sim = _mk(seed=5, running=0.0, nodes=6, jobs=4, tpj=4)
    sched = Scheduler(sim, arena=True)
    deleted = []

    def ingest():
        # runs inside the speculation window (while a decide is in
        # flight): delete one pending task the frozen epoch can see
        if not deleted:
            for j in sim.cluster.jobs.values():
                for uid, t in list(j.tasks.items()):
                    if t.status == TaskStatus.PENDING:
                        j.tasks.pop(uid)
                        sim.delta_sink.structural("task_set")
                        deleted.append(uid)
                        return 1
        return 0

    ex = PipelinedExecutor(sched, deterministic=True, ingest_fn=ingest)
    try:
        out = ex.step()
    finally:
        ex.close()
    assert deleted
    reasons = {d.reason for d in out.discards}
    dropped = {d.task_uid for d in out.discards}
    assert deleted[0] in dropped and "task_gone" in reasons
    assert all(b.task_uid != deleted[0] for b in out.binds)
    # the counter moved
    text = metrics().render()
    assert 'pipeline_discards_total{reason="task_gone"}' in text


def test_mid_window_cordon_discards_binds_to_that_node():
    sim = _mk(seed=9, running=0.0, nodes=5, jobs=4, tpj=4)
    sched = Scheduler(sim, arena=True)
    cordoned = []

    def ingest():
        if not cordoned:
            node = next(iter(sim.cluster.nodes.values()))
            node.unschedulable = True
            sim.delta_sink.node_dirty(node.name)
            cordoned.append(node.name)
            return 1
        return 0

    ex = PipelinedExecutor(sched, deterministic=True, ingest_fn=ingest)
    try:
        out = ex.step()
    finally:
        ex.close()
    assert cordoned
    assert all(b.node_name != cordoned[0] for b in out.binds)
    # every decision the frozen epoch aimed at the cordoned node is gone
    for d in out.discards:
        assert d.reason in ("node_unsched", "capacity_shrunk")


def test_journal_tee_records_even_when_arena_structural():
    sim = _mk(seed=1, running=0.0, nodes=4, jobs=2, tpj=3)
    from kube_arbitrator_tpu.cache.arena import SnapshotArena

    arena = SnapshotArena(sim)
    j = DeltaJournal()
    arena.journal = j
    # arena is structurally dirty from seeding; the journal still records
    assert arena._structural is not None
    sim.delta_sink.task_dirty("t1", "n1")
    sim.delta_sink.node_dirty("n2")
    assert "t1" in j.dirty_tasks and {"n1", "n2"} <= j.dirty_nodes
    j.reset()
    assert j.empty
    arena.structural("test_reason")
    assert j.structural == ["test_reason"]


def test_backpressure_counter_fires_when_ingest_outruns_decide():
    sim = _mk(seed=2, running=0.0, nodes=4, jobs=2, tpj=3)

    class SlowDecider:
        wants_device_pack = True
        last_action_ms = {}

        def __init__(self):
            self.inner = default_decider()

        def decide(self, st, config, pack_meta=None):
            time.sleep(0.15)
            return self.inner.decide(st, config)

    sched = Scheduler(sim, arena=True, decider=SlowDecider())
    pumps = []

    def ingest():
        pumps.append(1)
        return 1  # always "events pending": ingest outruns decide

    ex = PipelinedExecutor(
        sched, max_ingest_per_wait=3, wait_poll_s=0.001, ingest_fn=ingest
    )
    try:
        ex.step()
        ex.step()
    finally:
        ex.close()
    assert ex.backpressure_events >= 1
    assert "pipeline_backpressure_total" in metrics().render()


def test_occupancy_and_period_metrics_recorded():
    sim = _mk(seed=4, running=0.0, nodes=6, jobs=3, tpj=4)
    sched = Scheduler(sim, arena=True)
    ex = PipelinedExecutor(sched)
    try:
        ex.step()
        ex.step()
    finally:
        ex.close()
    text = metrics().render()
    assert "pipeline_cycle_period_seconds" in text
    assert 'pipeline_stage_busy_seconds_bucket{stage="decide"' in text
    assert 'pipeline_stage_occupancy{stage="decide"}' in text
    occ = ex.occupancy()
    assert set(occ) == {"ingest", "freeze", "decide", "revalidate", "actuate", "close"}


def test_decide_runs_off_the_ingest_thread():
    sim = _mk(seed=6, running=0.0, nodes=4, jobs=2, tpj=3)
    seen = []

    class Spy:
        wants_device_pack = True
        last_action_ms = {}

        def __init__(self):
            self.inner = default_decider()

        def decide(self, st, config, pack_meta=None):
            seen.append(threading.current_thread().name)
            return self.inner.decide(st, config)

    sched = Scheduler(sim, arena=True, decider=Spy())
    ex = PipelinedExecutor(sched)
    try:
        ex.step()
    finally:
        ex.close()
    assert seen and all(n.startswith("kat-pipe-decide") for n in seen)


# ---------------------------------------------------------------------------
# satellites: cached default decider, idle wait seam


def test_default_decider_is_cached_across_sessions():
    assert default_decider() is default_decider()
    s1 = Session(_mk(seed=1).cluster)
    s2 = Session(_mk(seed=1).cluster)
    assert s1._decider() is s2._decider()
    # an explicit decider still wins
    marker = object()
    assert Session(_mk(seed=1).cluster, decider=marker)._decider() is marker


def test_until_idle_wait_seam_blocks_then_times_out():
    sim = _mk(seed=13, running=0.0, nodes=6, jobs=2, tpj=3)
    calls = []

    def waiter():
        calls.append(1)
        if len(calls) == 1:
            # "an event arrived": inject fresh work, keep scheduling
            job = sim.add_job("late-job", queue="queue-000")
            sim.add_task(job, 500, 512 * 1024**2)
            return True
        return False  # timed out: exit

    sched = Scheduler(sim, arena=True, wait_for_event=waiter)
    sched.run(max_cycles=40)
    assert len(calls) == 2  # one wakeup with work, one timeout
    # the injected late task was actually placed after the wakeup
    late = [t for j in sim.cluster.jobs.values() if j.uid == "late-job"
            for t in j.tasks.values()]
    assert late and late[0].node_name


def test_live_cache_event_waiter():
    from kube_arbitrator_tpu.cache.fakeapi import FakeApiServer
    from kube_arbitrator_tpu.cache.live import LiveCache

    api = FakeApiServer()
    api.create("nodes", {"metadata": {"name": "n0"},
                         "status": {"allocatable": {"cpu": "4", "memory": "8Gi"}}})
    clock = [0.0]
    live = LiveCache(api, now_fn=lambda: clock[0])
    live.sync()  # initial LIST

    created = []

    def sleep(s):
        clock[0] += s
        if not created:  # an event shows up during the first wait
            api.create("queues", {"metadata": {"name": "q1"}, "spec": {"weight": 1}})
            created.append(1)

    wait = live.event_waiter(timeout_s=5.0, poll_s=1.0, sleep_fn=sleep)
    assert wait() is True         # the created queue's event woke it
    assert wait() is False        # nothing else arrives: timeout
    assert clock[0] >= 5.0


def test_on_events_callback_fires():
    from kube_arbitrator_tpu.cache.fakeapi import FakeApiServer
    from kube_arbitrator_tpu.cache.live import LiveCache

    api = FakeApiServer()
    api.create("nodes", {"metadata": {"name": "n0"},
                         "status": {"allocatable": {"cpu": "4", "memory": "8Gi"}}})
    live = LiveCache(api)
    got = []
    live.on_events = got.append
    live.sync()
    assert got and got[0] >= 1


# ---------------------------------------------------------------------------
# chaos: faults inside the speculation window


@pytest.mark.parametrize("seed", [0, 3])
def test_chaos_pipeline_profile_invariants_hold(seed):
    """Watch mangling / lease steals / rpc faults landing while frozen
    epochs are in flight: no_double_bind and no_overcommit (and the rest
    of the invariant set) must hold, and the run must be deterministic."""
    from kube_arbitrator_tpu.chaos.plan import PROFILES
    from kube_arbitrator_tpu.chaos.runner import run_chaos

    prof = PROFILES["pipeline"]
    r1 = run_chaos(seed=seed, cycles=8, profile=prof)
    assert not r1.breaches, [b.to_dict() for b in r1.breaches]
    r2 = run_chaos(seed=seed, cycles=8, profile=prof)
    assert r1.digests == r2.digests  # pure function of the plan
    assert r1.repro_json() == r2.repro_json()


def test_chaos_watch_reorder_never_inverts_one_objects_events():
    """The reorder fault models the cross-informer race; a real watch
    never reorders one object against itself (per-object rv is
    monotone), so the seam must skip same-object adjacent pairs."""
    from kube_arbitrator_tpu.chaos.clock import VirtualClock
    from kube_arbitrator_tpu.chaos.faults import ChaosApiServer, FaultInjector
    from kube_arbitrator_tpu.chaos.plan import FaultPlan, FaultSpec

    plan = FaultPlan(seed=0, specs=(
        FaultSpec(cycle=0, kind="watch_reorder", params=(("index", 0),)),
    ))
    clock = VirtualClock()
    inj = FaultInjector(plan, clock)
    api = ChaosApiServer(inj, clock)
    api.create("pods", {"metadata": {"namespace": "d", "name": "p1", "uid": "u1"},
                        "spec": {}, "status": {"phase": "Pending"}})
    rv0 = api._rv
    # two adjacent events for the SAME pod, then one for another object
    api.update_pod_condition("d", "p1", {"type": "PodScheduled", "status": "False"})
    api.update_pod_condition("d", "p1", {"type": "PodScheduled", "status": "False"})
    api.create("queues", {"metadata": {"name": "q9"}, "spec": {"weight": 1}})
    inj.begin_cycle(0)
    events = api.watch_all(rv0)
    p1_rvs = [ev[0] for ev in events
              if ev[1] == "pods" and ev[3]["metadata"]["name"] == "p1"]
    assert p1_rvs == sorted(p1_rvs), "same-object order inverted"
    assert inj.injected, "the fault should have landed on a cross-object pair"


def test_freeze_failure_after_commit_keeps_epoch_bookkeeping():
    """A failed pre-submit freeze (e.g. ArenaDivergence on the epoch
    check) must not erase the already-committed epoch's evidence: its
    stats land in history/metrics before the freeze error surfaces as
    the next cycle's failure."""
    from kube_arbitrator_tpu.cache.arena import ArenaDivergence

    sim = _mk(seed=8, running=0.0, nodes=5, jobs=3, tpj=3)
    sched = Scheduler(sim, arena=True)
    ex = PipelinedExecutor(sched)
    try:
        ex.step()  # fill + commit epoch 1
        n_hist = len(sched.history)
        # poison the arena: the NEXT pre-submit freeze's verify trips
        sched.arena.verify_every = 1
        sched.arena._packs_since_verify = 1
        sched.arena.corrupt("node_idle", 0, sched.arena._w["node_idle"][0] * 7)
        with pytest.raises(ArenaDivergence):
            ex.step()
        # the committed epoch's stats were recorded despite the raise
        assert len(sched.history) == n_hist + 1
        # and the executor recovers: the poisoned arena rebuilds
        out = ex.step()
        assert out.stats is sched.history[-1]
    finally:
        ex.close()
