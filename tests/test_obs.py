"""The served observability plane + Prometheus text conformance.

The conformance checker parses ``MetricsRegistry.render()`` line by line
against the exposition-format rules scrapers actually enforce: HELP/TYPE
emitted once per family and before its samples, cumulative ``le`` buckets
monotone, ``_count`` equal to the +Inf bucket.  The e2e test runs real sim
cycles (remote decider + leader elector, tracing on) with the obs server
up and asserts every endpoint serves coherent values — the acceptance
criteria for the observability plane.
"""
import json
import re
import urllib.error
import urllib.request

import pytest

from kube_arbitrator_tpu.obs import scheduler_status_fn, serve_obs
from kube_arbitrator_tpu.utils.flightrec import FlightRecorder
from kube_arbitrator_tpu.utils.metrics import METRIC_HELP, MetricsRegistry, metrics
from kube_arbitrator_tpu.utils.tracing import tracer

_SAMPLE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)(?P<labels>\{[^}]*\})? (?P<value>\S+)$"
)


def _strip_le(labels: str) -> str:
    inner = labels.strip("{}")
    parts = [p for p in inner.split(",") if p and not p.startswith("le=")]
    return ",".join(sorted(parts))


def check_promtext(text: str) -> None:
    """Assert ``text`` is conformant Prometheus exposition format:
    HELP before TYPE, TYPE once per family and before its samples,
    families contiguous, histogram le buckets cumulative-monotone with
    ``_count`` equal to the +Inf bucket per label set."""
    typed = {}            # family -> declared type
    current = None        # family of the block being read
    closed = set()        # families whose block has ended
    hist_buckets = {}     # (family, base labels) -> [cumulative counts]
    hist_inf = {}         # (family, base labels) -> +Inf bucket value
    hist_count = {}       # (family, base labels) -> _count value
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP"):
            _, _, fam, _ = line.split(" ", 3)
            assert fam not in typed, f"HELP for {fam} after its TYPE"
            continue
        if line.startswith("# TYPE"):
            _, _, fam, kind = line.split(" ", 3)
            assert fam not in typed, f"duplicate TYPE for {fam}"
            assert fam not in closed, f"family {fam} split into two blocks"
            assert kind in ("counter", "gauge", "histogram")
            typed[fam] = kind
            if current is not None:
                closed.add(current)
            current = fam
            continue
        m = _SAMPLE.match(line)
        assert m, f"unparseable sample line: {line!r}"
        name = m.group("name")
        fam = name if name in typed else re.sub(r"_(bucket|sum|count)$", "", name)
        assert fam in typed, f"sample {name} before any TYPE"
        assert fam == current, f"sample {name} outside its family block"
        value = float(m.group("value"))
        if typed[fam] == "histogram":
            labels = m.group("labels") or ""
            key = (fam, _strip_le(labels))
            if name.endswith("_bucket"):
                if 'le="+Inf"' in labels:
                    hist_inf[key] = value
                else:
                    hist_buckets.setdefault(key, []).append(value)
            elif name.endswith("_count"):
                hist_count[key] = value
    for key, buckets in hist_buckets.items():
        assert buckets == sorted(buckets), f"{key}: le buckets not monotone"
        assert key in hist_inf, f"{key}: no +Inf bucket"
        assert hist_inf[key] >= buckets[-1], f"{key}: +Inf below last bucket"
    for key, count in hist_count.items():
        assert hist_inf.get(key) == count, f"{key}: _count != +Inf bucket"


def _le_values(text: str, fam: str, labels_filter: str = "") -> list:
    out = []
    for line in text.splitlines():
        m = _SAMPLE.match(line) if line and not line.startswith("#") else None
        if m and m.group("name") == f"{fam}_bucket":
            labels = m.group("labels") or ""
            if labels_filter and labels_filter not in labels:
                continue
            out.append(float(m.group("value")))
    return out


def test_promtext_conformance_synthetic():
    r = MetricsRegistry(namespace="kat")
    r.counter_add("binds_total", 3)
    r.counter_add("watch_total", 1, labels={"phase": "list"})
    r.counter_add("watch_total", 9, labels={"phase": "watch"})
    r.gauge_set("pending_tasks", 7)
    for v in (0.002, 0.004, 0.1, 50.0, 200.0):  # incl. +Inf overflow
        r.observe("dur_seconds", v, labels={"phase": "kernel"})
        r.observe("dur_seconds", v / 2, labels={"phase": "decode"})
    text = r.render()
    check_promtext(text)
    # multi-label-set families emit TYPE exactly once
    assert text.count("# TYPE kat_watch_total counter") == 1
    assert text.count("# TYPE kat_dur_seconds histogram") == 1
    kernel_buckets = _le_values(text, "kat_dur_seconds", 'phase="kernel"')
    assert kernel_buckets == sorted(kernel_buckets)


def test_metric_help_table_covers_scheduler_families():
    """HELP text lives in ONE module-level table; the families the
    scheduler loop emits every cycle must all be declared there."""
    for fam in (
        "e2e_scheduling_duration_seconds",
        "cycle_phase_duration_seconds",
        "kernel_action_duration_seconds",
        "binds_total",
        "evicts_total",
        "pending_tasks",
        "rpc_decide_duration_seconds",
        "leader_renew_duration_seconds",
        # profiling / timeseries plane (PR 8)
        "xla_retraces_total",
        "xla_compile_seconds",
        "slo_burn_rate",
        "slo_burn_alerts_total",
    ):
        assert fam in METRIC_HELP, fam
    r = MetricsRegistry(namespace="kat")
    r.counter_add("binds_total", 1)
    assert "# HELP kat_binds_total" in r.render()


def test_obs_unknown_paths_share_one_counter_series(tmp_path):
    """Regression: a scanner probing random paths must not mint unbounded
    obs_requests_total label series in the process-wide registry."""
    reg = MetricsRegistry(namespace="kat")
    server, _t, url = serve_obs(registry=reg)
    try:
        for p in ("/wp-admin", "/.env", "/id/1", "/id/2", "/metrics"):
            try:
                _get(url + p)
            except urllib.error.HTTPError:
                pass
    finally:
        server.shutdown()
    text = reg.render()
    assert 'kat_obs_requests_total{path="other"} 4' in text
    assert "/wp-admin" not in text


def test_leader_demotion_paths_update_telemetry(tmp_path):
    """Regression: lease_fresh()'s actuation-fence demotion and
    release() must flip leader_is_leader and count a standby transition
    (renew() alone covered only one of the three demotion paths)."""
    from kube_arbitrator_tpu.framework import LeaderElector

    metrics().reset()
    clock = [1000.0]
    el = LeaderElector(lock_path=str(tmp_path / "l.lock"), identity="a",
                       now_fn=lambda: clock[0])
    assert el.try_acquire()
    assert metrics()._gauges[("leader_is_leader", ())] == 1.0
    clock[0] += el.renew_deadline_s + 1  # decide hung past the deadline
    assert el.lease_fresh() is False
    assert metrics()._gauges[("leader_is_leader", ())] == 0.0
    trans = metrics()._counters[("leader_transitions_total", (("to", "standby"),))]
    assert trans == 1.0
    assert el.try_acquire()  # re-acquire, then voluntary release
    el.release()
    assert metrics()._gauges[("leader_is_leader", ())] == 0.0


def _get(url: str):
    with urllib.request.urlopen(url, timeout=10) as resp:
        body = resp.read()
        return resp.status, body


@pytest.fixture
def obs_e2e(tmp_path):
    """3 sim cycles with the full plane: tracing on, file-lease leader,
    remote decider (in-process sidecar), flight recorder, obs server."""
    pytest.importorskip("grpc")
    from kube_arbitrator_tpu.cache.sim import generate_cluster
    from kube_arbitrator_tpu.framework import LeaderElector, Scheduler
    from kube_arbitrator_tpu.rpc import DecisionService, RemoteDecider, serve

    metrics().reset()
    tr = tracer()
    tr.reset()
    tr.enable()
    grpc_server, port = serve("127.0.0.1:0", service=DecisionService())
    sim = generate_cluster(num_nodes=16, num_jobs=4, tasks_per_job=4,
                           num_queues=2, seed=9)
    # generous lease timing: a cold first cycle compiles the staged
    # kernels and must not trip the actuation fence on a slow CI box
    elector = LeaderElector(lock_path=str(tmp_path / "leader.lock"),
                            identity="obs-test", lease_duration_s=300.0,
                            renew_deadline_s=120.0, retry_period_s=5.0)
    flight = FlightRecorder(capacity=16, dump_dir=str(tmp_path / "flight"))
    sched = Scheduler(
        sim, elector=elector, flight=flight,
        decider=RemoteDecider(f"127.0.0.1:{port}"),
    )
    sched.run(max_cycles=3, until_idle=False)
    server, thread, url = serve_obs(
        flight=flight, status_fn=scheduler_status_fn(sched)
    )
    try:
        yield sched, url
    finally:
        server.shutdown()
        sched.decider.close()
        grpc_server.stop(grace=None)
        elector.release()
        tr.enable(False)
        tr.reset()


def test_obs_plane_end_to_end(obs_e2e):
    """Acceptance: /metrics serves conformant Prometheus text including
    the new RPC / leader / per-action families; health + debug endpoints
    answer with values coherent with the scheduler's own state."""
    sched, url = obs_e2e

    status, body = _get(url + "/metrics")
    assert status == 200
    text = body.decode()
    check_promtext(text)
    ns = "kube_arbitrator_tpu"
    for fam in (
        f"{ns}_rpc_decide_duration_seconds",
        f"{ns}_leader_renew_duration_seconds",
        f"{ns}_rpc_codec_bytes_total",
        f"{ns}_e2e_scheduling_duration_seconds",
    ):
        assert f"# TYPE {fam}" in text, fam
    # action-labeled kernel histograms (staged runner, sidecar side)
    assert re.search(
        rf'{ns}_kernel_action_duration_seconds_count{{action="allocate"}} 3\b',
        text,
    )
    # counters agree with the scheduler's own history
    binds = sum(s.binds for s in sched.history)
    assert f"{ns}_binds_total {binds:g}" in text
    assert f"{ns}_cycles_total 3" in text
    assert f"{ns}_rpc_cycles_served_total 3" in text
    assert f"{ns}_leader_is_leader 1" in text

    status, body = _get(url + "/healthz")
    health = json.loads(body)
    assert status == 200 and health["ok"] and health["device_count"] >= 1
    assert health["leader"] is True and health["cycles"] == 3

    status, body = _get(url + "/readyz")
    assert status == 200 and json.loads(body)["ready"] is True

    status, body = _get(url + "/debug/cycles")
    cycles = json.loads(body)["cycles"]
    assert [c["seq"] for c in cycles] == [1, 2, 3]
    assert all(c["error"] is None for c in cycles)
    assert sum(c["digests"]["binds"] for c in cycles) == binds
    # every recorded cycle carries its spans and a correlation id
    assert all(c["corr_id"] and c["spans"] for c in cycles)

    corr = cycles[-1]["corr_id"]
    status, body = _get(url + f"/debug/trace/{corr}")
    assert status == 200
    trace = json.loads(body)
    names = {e["name"] for e in trace["traceEvents"]}
    assert {"cycle", "snapshot", "sidecar.decide"} <= names
    comps = {
        e["args"]["name"] for e in trace["traceEvents"] if e["ph"] == "M"
    }
    assert comps == {"scheduler", "sidecar"}

    with pytest.raises(urllib.error.HTTPError) as err:
        _get(url + "/debug/trace/nope")
    assert err.value.code == 404

    # the index route lists the endpoint catalog
    status, body = _get(url + "/")
    assert status == 200 and "/metrics" in json.loads(body)["endpoints"]


def test_replica_id_and_pool_route_multi_process_posture():
    """Fleet posture (rpc/pool.py satellite): port=0 binds an ephemeral
    port per replica (two servers never collide), /healthz + /readyz
    report the replica id so probes can tell N same-host replicas
    apart, and /debug/pool serves the pool status document."""
    import json

    from kube_arbitrator_tpu.rpc.pool import DecisionPool

    pool = DecisionPool(replicas=2, threaded=False)
    a_srv, _t, a_url = serve_obs(port=0, replica_id="r0", pool=pool)
    b_srv, _t, b_url = serve_obs(port=0, replica_id="r1")
    try:
        assert a_url != b_url  # ephemeral ports: no collision
        for url, rid in ((a_url, "r0"), (b_url, "r1")):
            _status, body = _get(url + "/healthz")
            assert json.loads(body)["replica"] == rid
            _status, body = _get(url + "/readyz")
            assert json.loads(body)["replica"] == rid
        _status, body = _get(a_url + "/debug/pool")
        doc = json.loads(body)
        assert [r["id"] for r in doc["replicas"]] == ["r0", "r1"]
        # no pool wired: the route answers with the wiring hint, not 404
        _status, body = _get(b_url + "/debug/pool")
        assert "error" in json.loads(body)
        _status, body = _get(a_url + "/")
        assert "/debug/pool" in json.loads(body)["endpoints"]
    finally:
        a_srv.shutdown()
        b_srv.shutdown()
