"""Columnar actuation & batched watch ingest: parity suites.

The PR's honest bar is "decisions bit-identical": the columnar
representation (cache/decode.BindColumn/EvictColumn) and the batched
event-block ingest (LiveCache._apply_event_blocks) are pure cost
optimizations — every observable (model state, arena pack bytes,
revalidation verdicts, actuation effects, delta-journal sets) must match
the object/scalar paths exactly.  Four planes are pinned here:

* the batched delta sink (``task_dirty_rows``) vs the scalar call
  sequence, on both the journal and the arena;
* the columnar revalidation gate vs the object gate — same kept sets,
  same discard kinds/reasons/details, across targeted scenarios for
  every discard reason and a randomized mix;
* columnar actuation on :class:`SimCluster` vs the object path —
  identical model mutations, failure diversion, events, and arena dirt,
  including the gang-atomic volume-failure branch;
* the randomized event-stream soak: batched ingest == scalar ingest on
  the same apiserver stream (model digest, arena pack tensors, and the
  decisions a cycle computes from them) across 3 seeds.
"""
import dataclasses
import random

import numpy as np
import pytest

from kube_arbitrator_tpu.api.types import TaskStatus
from kube_arbitrator_tpu.cache import (
    FakeApiServer,
    LiveCache,
    build_snapshot,
    generate_cluster,
)
from kube_arbitrator_tpu.cache.arena import SnapshotArena
from kube_arbitrator_tpu.cache.decode import (
    BindColumn,
    DecisionBatch,
    EvictColumn,
    decode_batch,
    decode_decisions,
)
from kube_arbitrator_tpu.cache.sim import BindIntent, EvictIntent
from kube_arbitrator_tpu.framework.conf import load_conf
from kube_arbitrator_tpu.ops.cycle import schedule_cycle
from kube_arbitrator_tpu.options import reset_options
from kube_arbitrator_tpu.pipeline import DeltaJournal
from kube_arbitrator_tpu.pipeline.revalidate import (
    revalidate_batch,
    revalidate_decisions,
)
from kube_arbitrator_tpu.utils.metrics import metrics

GB = 1024**3

FULL_CONF = load_conf(
    'actions: "reclaim, allocate, backfill, preempt"\n'
    "tiers:\n"
    "- plugins:\n"
    "  - name: priority\n"
    "  - name: gang\n"
    "- plugins:\n"
    "  - name: drf\n"
    "  - name: predicates\n"
    "  - name: proportion\n"
)


@pytest.fixture(autouse=True)
def _fresh():
    reset_options()
    metrics().reset()
    yield
    reset_options()
    metrics().reset()


# ---------------------------------------------------------------------------
# the batched delta sink


def test_task_dirty_rows_matches_scalar_sequence():
    """journal + arena: one batched call == the equivalent scalar
    sequence (dirty sets AND the journal's event count)."""
    sim_a = generate_cluster(num_nodes=4, num_jobs=3, tasks_per_job=3,
                             num_queues=2, seed=1)
    sim_b = generate_cluster(num_nodes=4, num_jobs=3, tasks_per_job=3,
                             num_queues=2, seed=1)
    arena_a = SnapshotArena(sim_a, verify_every=0)
    arena_b = SnapshotArena(sim_b, verify_every=0)
    arena_a.snapshot()  # clear the seed-structural state
    arena_b.snapshot()
    ja, jb = DeltaJournal(), DeltaJournal()
    arena_a.journal, arena_b.journal = ja, jb
    uids = ["u1", "u2", "u3", "u2"]
    nodes = ["n1", "", "n2", "n1"]
    arena_a.task_dirty_rows(uids, nodes)
    for u, n in zip(uids, nodes):
        arena_b.task_dirty(u, n)
    assert ja.dirty_tasks == jb.dirty_tasks == {"u1", "u2", "u3"}
    assert ja.dirty_nodes == jb.dirty_nodes == {"n1", "n2"}
    assert ja.events == jb.events == 4
    assert arena_a._dirty_tasks == arena_b._dirty_tasks
    assert arena_a._dirty_nodes == arena_b._dirty_nodes


def test_task_dirty_rows_respects_structural_state():
    """After a structural event the arena must NOT re-grow dirty sets
    (the next pack rebuilds anyway) — but the journal tee still records
    (the commit gate needs the window's deltas regardless)."""
    sim = generate_cluster(num_nodes=2, num_jobs=2, tasks_per_job=2,
                           num_queues=1, seed=2)
    arena = SnapshotArena(sim, verify_every=0)
    arena.snapshot()
    j = DeltaJournal()
    arena.journal = j
    arena.structural("relist")
    arena.task_dirty_rows(["u1"], ["n1"])
    assert not arena._dirty_tasks and not arena._dirty_nodes
    assert j.dirty_tasks == {"u1"} and j.dirty_nodes == {"n1"}


# ---------------------------------------------------------------------------
# columnar revalidation parity


def _columns_from_intents(snap, binds, evicts):
    """Build BindColumn/EvictColumn carrying exactly the given intents
    (ordinals resolved through the snapshot index)."""
    t_ord = {t.uid: i for i, t in enumerate(snap.index.tasks)}
    n_ord = {n.name: i for i, n in enumerate(snap.index.nodes)}
    bc = BindColumn(
        snap.index,
        np.asarray([t_ord[b.task_uid] for b in binds], np.int64),
        np.asarray([n_ord[b.node_name] for b in binds], np.int64),
    )
    ec = EvictColumn(
        snap.index,
        np.asarray([t_ord[e.task_uid] for e in evicts], np.int64),
    )
    return bc, ec


def _assert_gates_agree(cluster, snap, binds, evicts, journal):
    bc, ec = _columns_from_intents(snap, binds, evicts)
    kept_b, kept_e, disc_obj = revalidate_decisions(
        cluster, binds, evicts, journal
    )
    col_b, col_e, disc_col = revalidate_batch(cluster, bc, ec, journal)
    assert [(b.task_uid, b.node_name) for b in kept_b] == list(
        zip(col_b.uids, col_b.node_names)
    )
    assert [e.task_uid for e in kept_e] == col_e.uids
    assert [(d.kind, d.task_uid, d.reason, d.detail) for d in disc_obj] == [
        (d.kind, d.task_uid, d.reason, d.detail) for d in disc_col
    ]
    return disc_col


def test_revalidate_columnar_parity_every_reason():
    """One world staged so the gate fires every bind/evict discard
    reason (plus untouched pass-throughs): both gates must agree on
    kept order, reasons, AND detail strings."""
    sim = generate_cluster(num_nodes=6, num_jobs=4, tasks_per_job=4,
                           num_queues=2, seed=5, running_fraction=0.5)
    snap = build_snapshot(sim.cluster)
    index = {u: t for j in sim.cluster.jobs.values()
             for u, t in j.tasks.items()}
    pending = [t for t in index.values() if t.status == TaskStatus.PENDING]
    running = [t for t in index.values() if t.status == TaskStatus.RUNNING]
    assert len(pending) >= 6 and len(running) >= 2
    gone, bound, on_dead, on_cordon, fat, clean = pending[:6]
    j = DeltaJournal()
    # task_gone
    sim.cluster.jobs[gone.job_uid].tasks.pop(gone.uid)
    j.task_dirty(gone.uid)
    # already_bound
    bound.status = TaskStatus.BOUND
    bound.node_name = "node-00001"
    j.task_dirty(bound.uid)
    # node_gone / node_unsched
    sim.cluster.nodes.pop("node-00000")
    sim.cluster.nodes["node-00001"].unschedulable = True
    j.node_dirty("node-00000")
    j.node_dirty("node-00001")
    # capacity_shrunk (resource axis)
    node2 = sim.cluster.nodes["node-00002"]
    node2.idle = np.asarray(fat.resreq) * 0.5
    node2.releasing = np.zeros_like(node2.idle)
    j.node_dirty("node-00002")
    # not_evictable
    running[0].status = TaskStatus.RELEASING
    j.task_dirty(running[0].uid)
    binds = [
        BindIntent(task_uid=gone.uid, node_name="node-00003"),
        BindIntent(task_uid=bound.uid, node_name="node-00003"),
        BindIntent(task_uid=on_dead.uid, node_name="node-00000"),
        BindIntent(task_uid=on_cordon.uid, node_name="node-00001"),
        BindIntent(task_uid=fat.uid, node_name="node-00002"),
        BindIntent(task_uid=clean.uid, node_name="node-00003"),  # untouched
    ]
    evicts = [
        EvictIntent(task_uid=running[0].uid),
        EvictIntent(task_uid=running[1].uid),  # untouched
    ]
    discards = _assert_gates_agree(sim.cluster, snap, binds, evicts, j)
    assert sorted(d.reason for d in discards) == sorted([
        "task_gone", "already_bound", "node_gone", "node_unsched",
        "capacity_shrunk", "not_evictable",
    ])


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_revalidate_columnar_parity_randomized(seed):
    """Randomized churn: random dirty sets (including the structural
    check-everything flip) over random intent mixes — gates must agree
    verbatim.  Tentative capacity accounting is order-dependent, so the
    kept ORDER equality here is load-bearing."""
    rng = random.Random(seed)
    sim = generate_cluster(num_nodes=8, num_jobs=6, tasks_per_job=4,
                           num_queues=2, seed=seed, running_fraction=0.4)
    snap = build_snapshot(sim.cluster)
    index = {u: t for j in sim.cluster.jobs.values()
             for u, t in j.tasks.items()}
    pending = [t for t in index.values() if t.status == TaskStatus.PENDING]
    running = [t for t in index.values() if t.status == TaskStatus.RUNNING]
    node_names = sorted(sim.cluster.nodes)
    for round_i in range(5):
        j = DeltaJournal()
        if rng.random() < 0.2:
            j.structural_event("chaos")
        for t in rng.sample(pending, k=min(4, len(pending))):
            j.task_dirty(t.uid)
        for n in rng.sample(node_names, k=2):
            j.node_dirty(n)
        # random micro-churn the gate must adjudicate
        victim = rng.choice(pending)
        victim.status = rng.choice(
            [TaskStatus.PENDING, TaskStatus.BOUND, TaskStatus.RUNNING]
        )
        cordoned = rng.choice(node_names)
        sim.cluster.nodes[cordoned].unschedulable = rng.random() < 0.5
        binds = [
            BindIntent(task_uid=t.uid, node_name=rng.choice(node_names))
            for t in rng.sample(pending, k=min(8, len(pending)))
        ]
        evicts = [
            EvictIntent(task_uid=t.uid)
            for t in rng.sample(running, k=min(4, len(running)))
        ]
        _assert_gates_agree(sim.cluster, snap, binds, evicts, j)


def test_revalidate_batch_quiescent_returns_inputs_untouched():
    sim = generate_cluster(num_nodes=4, num_jobs=3, tasks_per_job=3,
                           num_queues=2, seed=9)
    snap = build_snapshot(sim.cluster)
    batch = decode_batch(snap, schedule_cycle(snap.tensors))
    out_b, out_e, disc = revalidate_batch(
        sim.cluster, batch.binds, batch.evicts, DeltaJournal()
    )
    assert out_b is batch.binds and out_e is batch.evicts and not disc


# ---------------------------------------------------------------------------
# columnar actuation parity (SimCluster)


def _world_pair(seed=3):
    mk = lambda: generate_cluster(num_nodes=8, num_jobs=6, tasks_per_job=4,
                                  num_queues=2, seed=seed)
    return mk(), mk()


def _model_digest(cluster):
    return {
        "jobs": {
            ju: {
                u: (t.status.name, t.node_name,
                    np.asarray(t.resreq).tolist())
                for u, t in sorted(j.tasks.items())
            }
            for ju, j in sorted(cluster.jobs.items())
        },
        "nodes": {
            n: (nd.idle.tolist(), nd.used.tolist(), nd.releasing.tolist(),
                sorted(nd.tasks))
            for n, nd in sorted(cluster.nodes.items())
        },
    }


def test_columnar_actuation_matches_object_path():
    """Same kernel decisions applied columnar vs object: identical model
    state, events, resync queues, failed sets, and arena dirt — with a
    volume-bind failure injected so the gang-atomic branch is covered."""
    sim_col, sim_obj = _world_pair()
    arena_col = SnapshotArena(sim_col, verify_every=0)
    arena_obj = SnapshotArena(sim_obj, verify_every=0)
    snap_c = arena_col.snapshot()
    snap_o = arena_obj.snapshot()
    dec_c = schedule_cycle(snap_c.tensors, tiers=FULL_CONF.tiers,
                           actions=FULL_CONF.actions)
    batch = decode_batch(snap_c, dec_c)
    binds, evicts = decode_decisions(
        snap_o, schedule_cycle(snap_o.tensors, tiers=FULL_CONF.tiers,
                               actions=FULL_CONF.actions)
    )
    assert len(batch.binds) == len(binds) and len(batch.binds) > 0
    # divert one mid-stream job's volumes: the whole job must fail
    # identically on both paths
    fail_uid = binds[len(binds) // 2].task_uid
    sim_col.volume_binder.fail_allocate_uids.add(fail_uid)
    sim_obj.volume_binder.fail_allocate_uids.add(fail_uid)
    failed_c = sim_col.apply_binds_columnar(batch.binds)
    failed_c += sim_col.apply_evicts_columnar(batch.evicts)
    failed_o = sim_obj.apply_binds(binds)
    failed_o += sim_obj.apply_evicts(evicts)
    assert failed_c == failed_o and fail_uid in failed_c
    assert _model_digest(sim_col.cluster) == _model_digest(sim_obj.cluster)
    assert [dataclasses.astuple(e) for e in sim_col.events] == [
        dataclasses.astuple(e) for e in sim_obj.events
    ]
    assert sim_col.resync_queue == sim_obj.resync_queue
    assert arena_col._dirty_tasks == arena_obj._dirty_tasks
    assert arena_col._dirty_nodes == arena_obj._dirty_nodes
    # and the packs both arenas build next are byte-identical
    pc, po = arena_col.snapshot(), arena_obj.snapshot()
    for f in dataclasses.fields(pc.tensors):
        a = getattr(pc.tensors, f.name)
        b = getattr(po.tensors, f.name)
        if a is None or not hasattr(a, "shape"):
            continue
        assert np.array_equal(np.asarray(a), np.asarray(b)), f.name


def test_column_sequence_compat():
    """The columns stay drop-in for object-path consumers: len/bool/
    iteration/indexing/== against intent lists."""
    sim = generate_cluster(num_nodes=4, num_jobs=4, tasks_per_job=3,
                           num_queues=2, seed=4)
    snap = build_snapshot(sim.cluster)
    dec = schedule_cycle(snap.tensors)
    batch = decode_batch(snap, dec)
    binds, evicts = decode_decisions(snap, dec)
    assert len(batch.binds) == len(binds)
    assert list(batch.binds) == binds
    assert batch.binds == binds and batch.evicts == evicts
    if binds:
        assert batch.binds[0] == binds[0]
        assert bool(batch.binds)
    empty = EvictColumn.empty(snap.index)
    assert not empty and empty == [] and len(empty) == 0
    sel = batch.binds.select(list(range(0, len(batch.binds), 2)))
    assert [b.task_uid for b in sel] == [b.task_uid for b in binds[::2]]
    assert isinstance(batch, DecisionBatch)


def test_pod_to_task_block_field_identical():
    """The block path's memoized wire translation must be field-identical
    to pod_to_task for every spec shape it can admit — plain, decorated
    (affinity/tolerations/ports/selector), multi-container, and repeated
    shapes through the shared memo."""
    from kube_arbitrator_tpu.cache.live import pod_to_task, pod_to_task_block

    plain = {
        "metadata": {"name": "a", "namespace": "ns", "uid": "u1",
                     "labels": {"app": "x"}},
        "spec": {"schedulerName": "kube-batch", "nodeName": "n1",
                 "priority": 3,
                 "containers": [{"resources": {"requests": {
                     "cpu": "500m", "memory": "2Gi"}}}]},
        "status": {"phase": "Running"},
    }
    decorated = {
        "metadata": {"name": "b", "uid": "u2"},
        "spec": {
            "nodeSelector": {"disk": "ssd"},
            "tolerations": [{"key": "k", "operator": "Exists",
                             "effect": "NoSchedule"}],
            "affinity": {
                "nodeAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": {
                        "nodeSelectorTerms": [{"matchExpressions": [
                            {"key": "zone", "operator": "In",
                             "values": ["z1", "z2"]}]}]}},
                "podAntiAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": [
                        {"labelSelector": {"matchLabels": {"app": "x"}},
                         "topologyKey": "kubernetes.io/hostname"}]},
            },
            "containers": [
                {"resources": {"requests": {"cpu": "1",
                                            "nvidia.com/gpu": "2"}},
                 "ports": [{"hostPort": 8080}]},
                {"resources": {"requests": {"memory": "1Gi"}}},
            ],
        },
        "status": {"phase": "Pending"},
    }
    memo: dict = {}
    for pod in (plain, decorated, plain):  # 3rd run exercises a memo hit
        ref = pod_to_task(pod, "job-1", "", 0)
        fast = pod_to_task_block(pod, "job-1", memo)
        for f in dataclasses.fields(ref):
            a, b = getattr(ref, f.name), getattr(fast, f.name)
            if f.name == "resreq":
                assert np.array_equal(a, b)
            else:
                assert a == b, f.name
        assert fast.resreq is not ref.resreq  # no shared arrays
    fast1 = pod_to_task_block(plain, "job-1", memo)
    fast2 = pod_to_task_block(plain, "job-1", memo)
    assert fast1.resreq is not fast2.resreq  # memo hands out copies


# ---------------------------------------------------------------------------
# the randomized event-stream ingest soak


def _pod(name, group, node="", phase="Pending", cpu="1", memory="1Gi",
         scheduler="kube-batch", priority=1):
    return {
        "metadata": {
            "name": name,
            "namespace": "default",
            "uid": f"uid-{name}",
            "annotations": {"scheduling.k8s.io/group-name": group}
            if group else {},
            "labels": {},
        },
        "spec": {
            "schedulerName": scheduler,
            "nodeName": node,
            "priority": priority,
            "containers": [
                {"resources": {"requests": {"cpu": cpu, "memory": memory}}}
            ],
        },
        "status": {"phase": phase},
    }


def _node(name, cpu="8", memory="16Gi", unschedulable=False):
    return {
        "metadata": {"name": name, "labels": {}},
        "status": {"allocatable": {"cpu": cpu, "memory": memory,
                                   "pods": 110}},
        "spec": {"unschedulable": unschedulable} if unschedulable else {},
    }


def _live_digest(live):
    c = live.cluster
    return {
        "jobs": {
            ju: (j.queue_uid, j.min_available, j.priority, {
                u: (t.status.name, t.node_name,
                    np.asarray(t.resreq).tolist(), t.priority)
                for u, t in sorted(j.tasks.items())
            })
            for ju, j in sorted(c.jobs.items())
        },
        "nodes": {
            n: (nd.idle.tolist(), nd.used.tolist(), nd.releasing.tolist(),
                sorted(nd.tasks), nd.unschedulable)
            for n, nd in sorted(c.nodes.items())
        },
        "others": sorted(t.uid for t in c.others),
        "queues": sorted(c.queues),
        "refs": dict(sorted(live._pod_ref.items())),
        "rv": live._watch_rv,
    }


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_ingest_soak_batched_equals_scalar(seed):
    """Two LiveCaches draining the SAME apiserver stream — one batched,
    one per-event — must agree after every pump on the full model
    digest AND the arena pack tensors; the cycle decisions computed
    from the final packs agree too.  The stream mixes row-local MODIFYs
    (the blockable shape) with structural churn (creates, deletes, job
    flips, cordons, foreign pods) so block flush boundaries are
    exercised, and the test asserts the batched path actually batched."""
    rng = random.Random(1000 + seed)
    api = FakeApiServer()
    for i in range(4):
        api.create("nodes", _node(f"n{i}"))
    api.create("queues", {"metadata": {"name": "default"},
                          "spec": {"weight": 1}})
    pods = {}  # name -> current dict
    for g in range(3):
        api.create("podgroups", {
            "metadata": {"name": f"pg{g}", "namespace": "default",
                         "creationTimestamp": 1.0},
            "spec": {"minMember": 1},
            "status": {},
        })
        for i in range(4):
            p = _pod(f"p{g}-{i}", f"pg{g}")
            pods[p["metadata"]["name"]] = p
            api.create("pods", p)
    batched = LiveCache(api, batch_ingest=True)
    scalar = LiveCache(api, batch_ingest=False)
    arena_b = SnapshotArena(batched, verify_every=1)  # verify every pack
    arena_s = SnapshotArena(scalar, verify_every=1)
    batched.sync()
    scalar.sync()
    n_new = 0
    for round_i in range(12):
        for _ in range(rng.randint(2, 6)):
            op = rng.random()
            if op < 0.55 and pods:
                # row-local MODIFY: phase/priority/node churn on an
                # existing pod (the blockable shape)
                name = rng.choice(sorted(pods))
                p = pods[name]
                p = _pod(
                    name,
                    p["metadata"]["annotations"].get(
                        "scheduling.k8s.io/group-name"),
                    node=p["spec"]["nodeName"] or (
                        rng.choice(["", "n0", "n1"])
                        if rng.random() < 0.4 else ""),
                    phase=rng.choice(["Pending", "Running", "Succeeded"]),
                    priority=rng.randint(1, 3),
                    scheduler=p["spec"]["schedulerName"],
                )
                pods[name] = p
                api.update("pods", p)
            elif op < 0.7:
                # structural: a new pod (sometimes foreign/assigned)
                n_new += 1
                foreign = rng.random() < 0.3
                p = _pod(
                    f"new-{n_new}",
                    None if foreign else f"pg{rng.randrange(3)}",
                    node=f"n{rng.randrange(4)}" if foreign else "",
                    phase="Running" if foreign else "Pending",
                    scheduler="default-scheduler" if foreign
                    else "kube-batch",
                )
                pods[p["metadata"]["name"]] = p
                api.create("pods", p)
            elif op < 0.8 and pods:
                name = rng.choice(sorted(pods))
                api.delete("pods", "default", name)
                pods.pop(name)
            elif op < 0.9:
                # job-membership flip: the scalar-fallback structural path
                name = rng.choice(sorted(pods)) if pods else None
                if name:
                    p = pods[name]
                    p = _pod(name, f"pg{rng.randrange(3)}",
                             node=p["spec"]["nodeName"],
                             phase=p["status"]["phase"],
                             scheduler=p["spec"]["schedulerName"])
                    pods[name] = p
                    api.update("pods", p)
            else:
                api.update("nodes", _node(
                    f"n{rng.randrange(4)}",
                    unschedulable=rng.random() < 0.5,
                ))
        nb = batched.sync()
        ns = scalar.sync()
        assert nb == ns, f"round {round_i}: applied counts diverged"
        assert _live_digest(batched) == _live_digest(scalar), (
            f"round {round_i}: model digests diverged"
        )
        pb = arena_b.snapshot()  # verify_every=1: oracle-checked packs
        ps = arena_s.snapshot()
        for f in dataclasses.fields(pb.tensors):
            a = getattr(pb.tensors, f.name)
            b = getattr(ps.tensors, f.name)
            if a is None or not hasattr(a, "shape"):
                continue
            assert np.array_equal(np.asarray(a), np.asarray(b)), (
                f"round {round_i}: pack tensor {f.name} diverged"
            )
    # the soak must have exercised the block path, not just fallen back
    assert metrics().counter_value(
        "cache_ingest_rows_total", {"path": "batched"}
    ) > 0
    # decisions from the final packs are bit-identical
    dec_b = schedule_cycle(pb.tensors, tiers=FULL_CONF.tiers,
                           actions=FULL_CONF.actions)
    dec_s = schedule_cycle(ps.tensors, tiers=FULL_CONF.tiers,
                           actions=FULL_CONF.actions)
    assert np.array_equal(np.asarray(dec_b.bind_mask),
                          np.asarray(dec_s.bind_mask))
    assert np.array_equal(np.asarray(dec_b.evict_mask),
                          np.asarray(dec_s.evict_mask))
    assert np.array_equal(np.asarray(dec_b.task_node),
                          np.asarray(dec_s.task_node))


def test_live_scheduler_cycle_with_batched_ingest_binds():
    """End-to-end: a Scheduler over a batched-ingest LiveCache binds
    through the apiserver and the watch round-trip (bound -> Running
    MODIFYs, the canonical blockable events) lands in the model."""
    from kube_arbitrator_tpu.framework import Scheduler

    api = FakeApiServer()
    for i in range(2):
        api.create("nodes", _node(f"n{i}"))
    api.create("queues", {"metadata": {"name": "default"},
                          "spec": {"weight": 1}})
    api.create("podgroups", {
        "metadata": {"name": "pg1", "namespace": "default",
                     "creationTimestamp": 1.0},
        "spec": {"minMember": 1}, "status": {},
    })
    for i in range(4):
        api.create("pods", _pod(f"p{i}", "pg1"))
    live = LiveCache(api, batch_ingest=True)
    sched = Scheduler(live)
    result = sched.run_once()
    assert len(result.binds) == 4
    live.sync()  # drain the bind/Running round-trip as event blocks
    job = live.cluster.jobs["default/pg1"]
    assert all(t.status == TaskStatus.RUNNING for t in job.tasks.values())
    assert metrics().counter_value(
        "cache_ingest_rows_total", {"path": "batched"}
    ) > 0


# ---------------------------------------------------------------------------
# evict columnar: certificate-gated batch commit


def _running_world_pair(seed=7):
    mk = lambda: generate_cluster(num_nodes=8, num_jobs=6, tasks_per_job=4,
                                  num_queues=2, seed=seed,
                                  running_fraction=0.5)
    return mk(), mk()


def _running_tasks(sim, n=6):
    return sorted(
        (t for j in sim.cluster.jobs.values() for t in j.tasks.values()
         if t.status == TaskStatus.RUNNING and t.node_name),
        key=lambda t: t.uid,
    )[:n]


def test_evict_columnar_certificate_batch_commit_parity():
    """A certifiable evict column must take the batch commit (the
    certificate proves failure-freedom) and leave model state, events,
    resync queue, arena dirt, and the NEXT pack identical to the scalar
    object path."""
    sim_col, sim_obj = _running_world_pair()
    arena_col = SnapshotArena(sim_col, verify_every=0)
    arena_obj = SnapshotArena(sim_obj, verify_every=0)
    snap = arena_col.snapshot()
    arena_obj.snapshot()
    victims = _running_tasks(sim_col)
    assert len(victims) >= 2
    intents = [EvictIntent(task_uid=t.uid) for t in victims]
    _, ec = _columns_from_intents(snap, [], intents)
    tasks = sim_col._resolve_rows(ec)
    assert sim_col._evict_batch_certificate(ec.uids, tasks) is not None
    failed_c = sim_col.apply_evicts_columnar(ec)
    failed_o = sim_obj.apply_evicts(intents)
    assert failed_c == failed_o == []
    assert sim_col.evictor.evicts == sim_obj.evictor.evicts
    assert _model_digest(sim_col.cluster) == _model_digest(sim_obj.cluster)
    assert [dataclasses.astuple(e) for e in sim_col.events] == [
        dataclasses.astuple(e) for e in sim_obj.events
    ]
    assert sim_col.resync_queue == sim_obj.resync_queue
    assert arena_col._dirty_tasks == arena_obj._dirty_tasks
    assert arena_col._dirty_nodes == arena_obj._dirty_nodes
    # node.tasks insertion order (the scalar pop/re-add moves the uid to
    # the end) must match too — the dict order feeds pack iteration
    for name, node in sim_col.cluster.nodes.items():
        assert list(node.tasks) == list(sim_obj.cluster.nodes[name].tasks)
    pc, po = arena_col.snapshot(), arena_obj.snapshot()
    for f in dataclasses.fields(pc.tensors):
        a = getattr(pc.tensors, f.name)
        b = getattr(po.tensors, f.name)
        if a is None or not hasattr(a, "shape"):
            continue
        assert np.array_equal(np.asarray(a), np.asarray(b)), f.name


def test_evict_columnar_injected_failure_falls_back_scalar():
    """An armed evictor failure must void the certificate and route the
    WHOLE column through the scalar chain — partial actuation, resync
    diversion, and event order bit-identical to the object path."""
    sim_col, sim_obj = _running_world_pair(seed=9)
    victims = _running_tasks(sim_col, n=5)
    assert len(victims) >= 3
    fail_uid = victims[len(victims) // 2].uid
    sim_col.evictor.fail_uids.add(fail_uid)
    sim_obj.evictor.fail_uids.add(fail_uid)
    snap = build_snapshot(sim_col.cluster)
    intents = [EvictIntent(task_uid=t.uid) for t in victims]
    _, ec = _columns_from_intents(snap, [], intents)
    tasks = sim_col._resolve_rows(ec)
    assert sim_col._evict_batch_certificate(ec.uids, tasks) is None
    failed_c = sim_col.apply_evicts_columnar(ec)
    failed_o = sim_obj.apply_evicts(intents)
    assert failed_c == failed_o == [fail_uid]
    assert sim_col.resync_queue == sim_obj.resync_queue == [fail_uid]
    assert _model_digest(sim_col.cluster) == _model_digest(sim_obj.cluster)
    assert [dataclasses.astuple(e) for e in sim_col.events] == [
        dataclasses.astuple(e) for e in sim_obj.events
    ]


def test_evict_columnar_duplicate_uid_voids_certificate():
    """Duplicate uids in one column are a doubt the certificate refuses
    (the second row's remove_task would raise mid-batch); the scalar
    fallback handles them with its per-row semantics."""
    sim, _ = _running_world_pair(seed=11)
    victims = _running_tasks(sim, n=2)
    snap = build_snapshot(sim.cluster)
    intents = [EvictIntent(task_uid=victims[0].uid)] * 2
    _, ec = _columns_from_intents(snap, [], intents)
    tasks = sim._resolve_rows(ec)
    assert sim._evict_batch_certificate(ec.uids, tasks) is None
