"""Observability: histograms, Prometheus rendering, scheduler phase timings."""
import math

from kube_arbitrator_tpu.cache import SimCluster
from kube_arbitrator_tpu.framework import Scheduler
from kube_arbitrator_tpu.utils.metrics import Histogram, MetricsRegistry, metrics

GB = 1024**3


def test_histogram_quantiles_and_mean():
    h = Histogram()
    for v in [0.001, 0.002, 0.004, 0.008, 0.1, 1.0]:
        h.observe(v)
    assert h.n == 6
    assert abs(h.total - 1.115) < 1e-9
    assert 0.001 <= h.quantile(0.5) <= 0.01
    assert h.quantile(0.99) >= 0.1
    assert not math.isnan(h.mean)


def test_registry_render_prometheus_text():
    r = MetricsRegistry(namespace="kat")
    r.describe("binds_total", "Committed binds.")
    r.counter_add("binds_total", 3)
    r.gauge_set("pending_tasks", 7)
    r.observe("cycle_phase_duration_seconds", 0.05, labels={"phase": "kernel"})
    text = r.render()
    assert "# TYPE kat_binds_total counter" in text
    assert "kat_binds_total 3" in text
    assert "kat_pending_tasks 7" in text
    assert 'kat_cycle_phase_duration_seconds_bucket{phase="kernel",le="+Inf"} 1' in text
    assert 'kat_cycle_phase_duration_seconds_count{phase="kernel"} 1' in text
    # cumulative bucket counts are monotone
    counts = [
        int(line.rsplit(" ", 1)[1])
        for line in text.splitlines()
        if line.startswith("kat_cycle_phase_duration_seconds_bucket")
    ]
    assert counts == sorted(counts)


def test_scheduler_records_phase_timings():
    metrics().reset()
    sim = SimCluster()
    sim.add_queue("default")
    sim.add_node("n1", cpu_milli=4000, memory=8 * GB)
    job = sim.add_job("j1")
    sim.add_task(job, cpu_milli=500, memory=GB)
    sched = Scheduler(sim)
    sched.run_once()
    s = sched.history[-1]
    assert s.kernel_ms > 0 and s.snapshot_ms > 0
    # phases are sub-measurements of the cycle
    assert s.cycle_ms >= s.kernel_ms
    m = metrics()
    assert m.histogram("e2e_scheduling_duration_seconds").n == 1
    assert m.histogram("cycle_phase_duration_seconds", {"phase": "kernel"}).n == 1
    text = m.render()
    assert "kube_arbitrator_tpu_binds_total 1" in text
