"""Observability: histograms, Prometheus rendering, scheduler phase timings."""
import math
import threading

from kube_arbitrator_tpu.cache import SimCluster
from kube_arbitrator_tpu.framework import Scheduler
from kube_arbitrator_tpu.utils.metrics import Histogram, MetricsRegistry, metrics

GB = 1024**3


def test_histogram_quantiles_and_mean():
    h = Histogram()
    for v in [0.001, 0.002, 0.004, 0.008, 0.1, 1.0]:
        h.observe(v)
    assert h.n == 6
    assert abs(h.total - 1.115) < 1e-9
    assert 0.001 <= h.quantile(0.5) <= 0.01
    assert h.quantile(0.99) >= 0.1
    assert not math.isnan(h.mean)


def test_histogram_quantile_overflow_bucket_is_marked():
    """Regression: a rank landing in the +Inf overflow bucket must not
    silently cap the estimate — the value is the last finite bound (never
    NaN) and ``quantile_capped`` flags it as a lower bound."""
    h = Histogram()
    top = h.buckets[-1]
    for v in (0.001, 0.002):
        h.observe(v)
    for _ in range(8):
        h.observe(top * 10)  # all land in the +Inf bucket
    v99, capped = h.quantile_capped(0.99)
    assert capped is True
    assert v99 == top and not math.isnan(v99)
    assert h.quantile(0.99) == top  # plain accessor agrees, NaN-free
    # low quantiles that stay in finite buckets are uncapped
    v10, capped10 = h.quantile_capped(0.1)
    assert capped10 is False and v10 <= 0.002
    # empty histogram: NaN estimate, not capped
    v, c = Histogram().quantile_capped(0.5)
    assert math.isnan(v) and c is False


def test_render_keeps_full_precision_on_large_counters():
    """Regression: %g rendering quantized counters past ~1e6 significant
    digits, flattening rate() on high-magnitude families like
    rpc_codec_bytes_total; integral values must render exactly."""
    r = MetricsRegistry(namespace="kat")
    r.counter_add("bytes_total", 12345678.0)
    r.counter_add("bytes_total", 1.0)
    r.gauge_set("staleness_seconds", 0.1234567890123)
    text = r.render()
    assert "kat_bytes_total 12345679\n" in text
    assert "kat_staleness_seconds 0.1234567890123\n" in text


def test_registry_is_thread_safe_under_concurrent_writes():
    """The sidecar's handler threads and the scheduler loop write the one
    registry concurrently while the obs server renders it (the KAT-LCK
    failure mode): hammer all three op kinds from 8 threads and render
    in the middle; totals must come out exact."""
    r = MetricsRegistry(namespace="kat")
    threads, per_thread = 8, 500
    renders = []

    def writer(i):
        for k in range(per_thread):
            r.counter_add("ops_total", 1, labels={"t": str(i % 2)})
            r.observe("dur_seconds", 0.001 * (k % 50 + 1))
            r.gauge_set("depth", float(k))
            if k % 100 == 0:
                renders.append(r.render())

    ts = [threading.Thread(target=writer, args=(i,)) for i in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    text = r.render()
    total = sum(
        float(line.rsplit(" ", 1)[1])
        for line in text.splitlines()
        if line.startswith("kat_ops_total{")
    )
    assert total == threads * per_thread
    h = r.histogram("dur_seconds")
    assert h.n == threads * per_thread
    assert sum(h.counts) == h.n
    assert all(renders)  # every mid-write render produced parseable text


def test_registry_render_prometheus_text():
    r = MetricsRegistry(namespace="kat")
    r.describe("binds_total", "Committed binds.")
    r.counter_add("binds_total", 3)
    r.gauge_set("pending_tasks", 7)
    r.observe("cycle_phase_duration_seconds", 0.05, labels={"phase": "kernel"})
    text = r.render()
    assert "# TYPE kat_binds_total counter" in text
    assert "kat_binds_total 3" in text
    assert "kat_pending_tasks 7" in text
    assert 'kat_cycle_phase_duration_seconds_bucket{phase="kernel",le="+Inf"} 1' in text
    assert 'kat_cycle_phase_duration_seconds_count{phase="kernel"} 1' in text
    # cumulative bucket counts are monotone
    counts = [
        int(line.rsplit(" ", 1)[1])
        for line in text.splitlines()
        if line.startswith("kat_cycle_phase_duration_seconds_bucket")
    ]
    assert counts == sorted(counts)


def test_scheduler_records_phase_timings():
    metrics().reset()
    sim = SimCluster()
    sim.add_queue("default")
    sim.add_node("n1", cpu_milli=4000, memory=8 * GB)
    job = sim.add_job("j1")
    sim.add_task(job, cpu_milli=500, memory=GB)
    sched = Scheduler(sim)
    sched.run_once()
    s = sched.history[-1]
    assert s.kernel_ms > 0 and s.snapshot_ms > 0
    # phases are sub-measurements of the cycle
    assert s.cycle_ms >= s.kernel_ms
    m = metrics()
    assert m.histogram("e2e_scheduling_duration_seconds").n == 1
    assert m.histogram("cycle_phase_duration_seconds", {"phase": "kernel"}).n == 1
    text = m.render()
    assert "kube_arbitrator_tpu_binds_total 1" in text


def test_gated_rounds_variant_label_mapping():
    """The staged runner encodes gate-served rounds as an ":gated"
    suffix in action_rounds; the scheduler's metric emitter must map it
    to the variant="gated" series of kernel_rounds_total{action} (and
    leave plain actions label-compatible with the pre-gate series)."""
    from kube_arbitrator_tpu.cache import SimCluster
    from kube_arbitrator_tpu.framework.scheduler import CycleStats, Scheduler
    from kube_arbitrator_tpu.utils.metrics import metrics

    m = metrics()
    m.reset()
    sim = SimCluster()
    sim.add_queue("q")
    sim.add_node("n0", cpu_milli=1000, memory=1024)
    sched = Scheduler(sim)
    sched._record_metrics(
        CycleStats(cycle_ms=1.0, snapshot_ms=0.1, binds=0, evicts=0,
                   pending_before=0),
        {"preempt": 3.0},
        {"preempt": 62, "preempt:gated": 57, "reclaim": 58},
    )
    assert m.counter_value(
        "kernel_rounds_total", {"action": "preempt"}
    ) == 62
    assert m.counter_value(
        "kernel_rounds_total", {"action": "preempt", "variant": "gated"}
    ) == 57
    assert m.counter_value(
        "kernel_rounds_total", {"action": "reclaim"}
    ) == 58
