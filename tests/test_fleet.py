"""Fleet observability plane (utils/fleet.py): cross-tenant ledger math,
conservation, starvation clocks, batch occupancy accounting, pool-batch
trace stitching, shard rollups, and the 2x4 threaded e2e reconciliation
against the per-tenant audit ledgers.

The load-bearing property is RECONCILIATION: the fleet view is a join of
planes that already exist (PR 10 audit records, the pool decision log,
pool_requests_total outcomes) — every fleet number must be derivable
from, and checked against, its sources.  A fleet ledger that can drift
from them silently would report fairness over fiction, which is why the
chaos canary (``--disable fleet-ledger``) must breach.
"""
import json
import threading
import urllib.request

import pytest

from kube_arbitrator_tpu.cache import build_snapshot, generate_cluster
from kube_arbitrator_tpu.framework import Scheduler
from kube_arbitrator_tpu.framework.conf import SchedulerConfig
from kube_arbitrator_tpu.obs import serve_obs
from kube_arbitrator_tpu.rpc.pool import DecisionPool, PoolClient
from kube_arbitrator_tpu.utils.audit import AuditLog
from kube_arbitrator_tpu.utils.fleet import (
    FleetPlane,
    SkewBurnMonitor,
    shard_rollup_values,
    water_fill,
)
from kube_arbitrator_tpu.utils.flightrec import FlightRecorder
from kube_arbitrator_tpu.utils.metrics import MetricsRegistry
from kube_arbitrator_tpu.utils.timeseries import CycleSampler, TimeSeriesRing
from tests.test_obs import check_promtext


class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


class _Flight:
    """Anomaly-recording flight stub (no global metrics side effects)."""

    def __init__(self):
        self.anomalies = []

    def anomaly(self, kind, detail=""):
        self.anomalies.append((kind, detail))


def _record(seq=1, corr="c000001-ab", fairness=(), cluster_total=(10.0, 10.0, 10.0)):
    return {
        "seq": seq, "corr_id": corr, "ts": 0.0,
        "fairness": list(fairness), "cluster_total": list(cluster_total),
        "binds": [], "evictions": [], "gangs": {},
    }


def _qrow(deserved, allocated, pending=0, share_des=0.0, share_alloc=0.0):
    return {
        "queue": "q", "deserved": list(deserved), "allocated": list(allocated),
        "share_deserved": share_des, "share_allocated": share_alloc,
        "pending": pending,
    }


# ---- water-fill ----


def test_water_fill_clamps_to_demand_and_capacity():
    # spare capacity: everyone gets their demand
    assert water_fill([0.2, 0.3], [1, 1], 1.0) == [0.2, 0.3]
    # contention splits evenly, small demands capped then spare re-filled
    assert water_fill([0.5, 0.9, 0.1], [1, 1, 1], 1.0) == [0.45, 0.45, 0.1]
    # weights tilt the level
    assert water_fill([0.9, 0.9], [2, 1], 0.9) == pytest.approx([0.6, 0.3])
    # zero weight = entitled to nothing; zero capacity = nothing at all
    assert water_fill([0.5, 0.5], [0, 1], 1.0) == [0.0, 0.5]
    assert water_fill([0.5], [1], 0.0) == [0.0]
    assert water_fill([], [], 1.0) == []


def test_water_fill_conserves_capacity():
    for demands in ([0.9, 0.8, 0.7, 0.2], [0.1, 0.1], [1.0, 1.0, 1.0]):
        ent = water_fill(demands, [1.0] * len(demands), 1.0)
        assert sum(ent) <= 1.0 + 1e-9
        assert all(e <= d + 1e-9 for e, d in zip(ent, demands))


# ---- the window join ----


def test_window_joins_tenant_records_exactly():
    """Fleet totals are the sums of the tenant records, and per-tenant
    realized shares are dominant shares of the aggregate capacity."""
    clock = _Clock()
    fleet = FleetPlane(registry=MetricsRegistry(namespace="t"), now_fn=clock)
    fleet.observe_tenant("t0", _record(fairness=[
        _qrow([4, 2, 0], [3, 1, 0], pending=2),
        _qrow([2, 2, 0], [2, 2, 0], pending=0),
    ]))
    fleet.observe_tenant("t1", _record(fairness=[
        _qrow([8, 0, 0], [6, 0, 0], pending=5),
    ]))
    for _ in range(3):
        fleet.note_outcome("t0", "served")
    fleet.note_outcome("t1", "served")
    fleet.note_outcome("t1", "shed")
    w = fleet.close_window(cycle=7)
    assert w.cycle == 7 and len(w.tenants) == 2
    # capacity = sum of tenant cluster totals, allocated = sum of rows
    assert w.totals["capacity"] == [20.0, 20.0, 20.0]
    assert w.totals["allocated"] == [11.0, 3.0, 0.0]
    assert w.conservation["ok"]
    by = {r["tenant"]: r for r in w.tenants}
    # realized = dominant share of the aggregate: t0 = 5/20, t1 = 6/20
    assert by["t0"]["realized"] == pytest.approx(0.25)
    assert by["t1"]["realized"] == pytest.approx(0.30)
    # demand = dominant share of summed deserved (weight 1)
    assert by["t0"]["demand"] == pytest.approx(6 / 20)
    assert by["t1"]["demand"] == pytest.approx(8 / 20)
    # no contention (sum <= 1): entitled == demand
    assert by["t0"]["entitled"] == pytest.approx(6 / 20)
    # outcome attribution
    assert by["t0"]["served"] == 3 and by["t0"]["shed"] == 0
    assert by["t1"]["served"] == 1 and by["t1"]["shed"] == 1
    assert w.totals["served"] == 4 and w.totals["shed"] == 1
    assert by["t0"]["pending"] == 2 and by["t1"]["pending"] == 5
    # outcome counts reset per window; records carry over
    w2 = fleet.close_window()
    by2 = {r["tenant"]: r for r in w2.tenants}
    assert by2["t0"]["served"] == 0 and by2["t0"]["realized"] == by["t0"]["realized"]


def test_uncapped_deserved_clamps_to_tenant_capacity():
    fleet = FleetPlane(registry=MetricsRegistry(namespace="t"))
    fleet.observe_tenant("t0", _record(fairness=[
        _qrow([1e30, 1e30, 1e30], [5, 0, 0]),
    ], cluster_total=(10, 10, 10)))
    w = fleet.close_window()
    row = w.tenants[0]
    # entitled to everything it owns (10/10 of the 10/10 aggregate),
    # never to phantom capacity
    assert row["demand"] == pytest.approx(1.0)


def test_share_unit_fallback_for_records_without_cluster_total():
    fleet = FleetPlane(registry=MetricsRegistry(namespace="t"))
    fleet.observe_tenant("t0", {
        "seq": 1, "corr_id": "", "fairness": [
            {"queue": "a", "share_deserved": 0.6, "share_allocated": 0.4,
             "pending": 1},
        ],
    })
    w = fleet.close_window()
    assert w.tenants[0]["demand"] == pytest.approx(0.6)
    assert w.tenants[0]["realized"] == pytest.approx(0.4)


def test_mixed_producers_fallback_stays_visible_no_phantom_imbalance():
    """A pre-fleet (share-unit) tenant next to exact producers: its
    row stays in own-cluster shares (not drowned by the resource-unit
    aggregate), and its summed dominant shares — which can legitimately
    exceed 1 across differently-dominant queues — must NOT trip the
    conservation check."""
    clock = _Clock()
    flight = _Flight()
    fleet = FleetPlane(registry=MetricsRegistry(namespace="t"), flight=flight,
                       starvation_slo_s=10.0, now_fn=clock)
    fleet.observe_tenant("exact", _record(fairness=[
        _qrow([24000, 0, 0], [24000, 0, 0]),
    ], cluster_total=(48000, 0, 0)))
    # two queues dominant on different dims: shares sum to 1.2 while
    # actual usage fits the cluster
    fleet.observe_tenant("old", {
        "seq": 1, "corr_id": "", "fairness": [
            {"queue": "a", "share_deserved": 0.6, "share_allocated": 0.6,
             "pending": 3},
            {"queue": "b", "share_deserved": 0.6, "share_allocated": 0.6,
             "pending": 0},
        ],
    })
    w = fleet.close_window()
    assert w.conservation["ok"], w.conservation  # no phantom fleet_imbalance
    assert not [k for k, _ in flight.anomalies if k == "fleet_imbalance"]
    by = {r["tenant"]: r for r in w.tenants}
    # the exact tenant accounts against the resource-unit aggregate
    assert by["exact"]["realized"] == pytest.approx(0.5)
    # the fallback tenant accounts in its OWN share units — visible,
    # not ~0 against a 48000-millicore capacity dimension
    assert by["old"]["realized"] == pytest.approx(1.2)
    assert by["old"]["demand"] == pytest.approx(1.0)  # deserved clamped
    assert w.totals["capacity"] == [48000.0, 0.0, 0.0]
    assert w.totals["allocated"] == [24000.0, 0.0, 0.0]
    # a genuinely starving fallback tenant still runs its clock
    fleet.observe_tenant("old", {
        "seq": 2, "corr_id": "", "fairness": [
            {"queue": "a", "share_deserved": 0.9, "share_allocated": 0.0,
             "pending": 3},
        ],
    })
    fleet.close_window()
    clock.t += 20.0
    w3 = fleet.close_window()
    by3 = {r["tenant"]: r for r in w3.tenants}
    assert by3["old"]["starvation_s"] > 0
    assert [k for k, _ in flight.anomalies].count("fleet_starvation") == 1


def test_tenant_weights_tilt_entitlements_only_under_contention():
    # contention: both tenants demand their full cluster on DIFFERENT
    # dominant dims, so the fleet-level demands sum to 2.0 > 1 — the
    # weighted fill gives t0 3x t1's entitlement
    fleet = FleetPlane(
        registry=MetricsRegistry(namespace="t"), weights={"t0": 3.0},
    )
    fleet.observe_tenant("t0", _record(fairness=[
        _qrow([10, 0, 0], [10, 0, 0]),
    ], cluster_total=(10, 0, 0)))
    fleet.observe_tenant("t1", _record(fairness=[
        _qrow([0, 10, 0], [0, 10, 0]),
    ], cluster_total=(0, 10, 0)))
    w = fleet.close_window()
    by = {r["tenant"]: r for r in w.tenants}
    assert by["t0"]["entitled"] == pytest.approx(0.75)
    assert by["t1"]["entitled"] == pytest.approx(0.25)


def test_weight_never_entitles_past_demand():
    """The weight enters exactly once (the fill level): without
    contention a weighted tenant is entitled to its demand, never more —
    a fully-served weighted tenant must not read as starving."""
    fleet = FleetPlane(
        registry=MetricsRegistry(namespace="t"), weights={"t0": 3.0},
    )
    for t in ("t0", "t1"):
        fleet.observe_tenant(t, _record(fairness=[
            _qrow([5, 0, 0], [5, 0, 0], pending=2),
        ], cluster_total=(10, 0, 0)))
    w = fleet.close_window()
    by = {r["tenant"]: r for r in w.tenants}
    # each demands 0.25 of the aggregate and gets it: delta 0, no
    # phantom starvation for the weighted tenant
    for t in ("t0", "t1"):
        assert by[t]["entitled"] == pytest.approx(0.25)
        assert by[t]["delta"] == pytest.approx(0.0)
        assert by[t]["starvation_s"] == 0.0


# ---- conservation -> fleet_imbalance ----


def test_conservation_breach_fires_fleet_imbalance_dump(tmp_path):
    flight = FlightRecorder(capacity=4, dump_dir=str(tmp_path))
    reg = MetricsRegistry(namespace="t")
    fleet = FleetPlane(registry=reg, flight=flight)
    # a corrupted ledger: one tenant claims 25 allocated of a 10-unit
    # cluster — the per-dimension sum must blow the aggregate
    fleet.observe_tenant("t0", _record(fairness=[
        _qrow([5, 0, 0], [25, 0, 0]),
    ], cluster_total=(10, 0, 0)))
    fleet.observe_tenant("t1", _record(fairness=[
        _qrow([5, 0, 0], [5, 0, 0]),
    ], cluster_total=(10, 0, 0)))
    w = fleet.close_window()
    assert not w.conservation["ok"]
    v = w.conservation["violations"][0]
    assert v["allocated"] == 30.0 and v["capacity"] == 20.0
    assert reg.counter_value("fleet_conservation_breaches_total") == 1
    dumps = list(tmp_path.glob("flight-*-fleet_imbalance.json"))
    assert len(dumps) == 1
    payload = json.loads(dumps[0].read_text())
    assert payload["kind"] == "fleet_imbalance"
    assert "allocated 30" in payload["detail"]


def test_conservation_holds_on_honest_ledgers():
    fleet = FleetPlane(registry=MetricsRegistry(namespace="t"))
    fleet.observe_tenant("t0", _record(fairness=[
        _qrow([10, 10, 0], [10, 8, 0]),
    ], cluster_total=(10, 10, 0)))
    w = fleet.close_window()
    assert w.conservation["ok"] and not w.conservation["violations"]


# ---- starvation clocks ----


def test_starvation_clock_runs_only_while_under_entitled():
    clock = _Clock()
    flight = _Flight()
    fleet = FleetPlane(
        registry=MetricsRegistry(namespace="t"), flight=flight,
        starvation_slo_s=30.0, now_fn=clock,
    )
    starving = _record(fairness=[
        _qrow([8, 0, 0], [1, 0, 0], pending=4),
    ], cluster_total=(10, 0, 0))
    fat = _record(fairness=[
        _qrow([2, 0, 0], [9, 0, 0], pending=4),
    ], cluster_total=(10, 0, 0))
    fleet.observe_tenant("t0", starving)
    fleet.observe_tenant("t1", fat)
    fleet.close_window()
    clock.t += 40.0
    w = fleet.close_window()
    by = {r["tenant"]: r for r in w.tenants}
    assert by["t0"]["starvation_s"] == pytest.approx(40.0)
    # backlogged but over-entitled = queuing, not starving
    assert by["t1"]["starvation_s"] == 0.0
    kinds = [k for k, _ in flight.anomalies]
    assert kinds.count("fleet_starvation") == 1  # once per episode
    # still starving: no re-fire
    clock.t += 40.0
    fleet.close_window()
    assert [k for k, _ in flight.anomalies].count("fleet_starvation") == 1
    # progress (at entitlement) re-arms the episode
    fleet.observe_tenant("t0", fat)
    fleet.close_window()
    clock.t += 40.0
    fleet.observe_tenant("t0", starving)
    clock.t += 40.0
    fleet.close_window()
    assert [k for k, _ in flight.anomalies].count("fleet_starvation") == 2


def test_fully_shed_tenant_runs_the_starvation_clock():
    """A tenant shed on every request never commits a cycle (no audit
    record, no pending count) — denial of service must still run its
    clock, or the most-starved tenant reports starvation_s 0."""
    clock = _Clock()
    flight = _Flight()
    fleet = FleetPlane(
        registry=MetricsRegistry(namespace="t"), flight=flight,
        starvation_slo_s=30.0, now_fn=clock,
    )
    fleet.note_outcome("t0", "shed")
    fleet.close_window()
    clock.t += 40.0
    fleet.note_outcome("t0", "shed")
    w = fleet.close_window()
    row = w.tenants[0]
    assert row["starvation_s"] == pytest.approx(40.0)
    anoms = [d for k, d in flight.anomalies if k == "fleet_starvation"]
    assert len(anoms) == 1 and "shed" in anoms[0]
    # service re-arms the episode
    fleet.note_outcome("t0", "served")
    w2 = fleet.close_window()
    assert w2.tenants[0]["starvation_s"] == 0.0


def test_idle_tenants_evicted_after_retention():
    from kube_arbitrator_tpu.utils.fleet import TENANT_IDLE_EVICT_WINDOWS

    fleet = FleetPlane(registry=MetricsRegistry(namespace="t"))
    fleet.observe_tenant("gone", _record(fairness=[_qrow([1, 0, 0], [1, 0, 0])]))
    w = None
    for _ in range(TENANT_IDLE_EVICT_WINDOWS + 2):
        fleet.note_outcome("alive", "served")
        w = fleet.close_window()
    tenants = {r["tenant"] for r in w.tenants}
    assert tenants == {"alive"}, tenants  # gone evicted, alive retained


# ---- batch accounting + promtext ----


def test_batch_accounting_per_bucket_and_promtext():
    reg = MetricsRegistry(namespace="kat")
    fleet = FleetPlane(registry=reg)
    fleet.observe_batch("batch-000001", 4, 3, "r0", True, 12.0,
                        tenants=["a", "b", "c"])
    fleet.observe_batch("batch-000002", 4, 4, "r0", False, 9.0,
                        tenants=["a", "b", "c", "d"])
    fleet.observe_batch("batch-000003", 1, 1, "r1", True, 5.0, tenants=["a"])
    assert reg.gauge_value("pool_batch_occupancy", {"bucket": "4"}) == 1.0
    assert reg.counter_value("pool_batch_padding_total", {"bucket": "4"}) == 1
    assert reg.counter_value(
        "pool_batch_launches_total", {"bucket": "4", "compile": "compile"}
    ) == 1
    assert reg.counter_value(
        "pool_batch_launches_total", {"bucket": "4", "compile": "reuse"}
    ) == 1
    rows = fleet.batch_ring.rows()
    assert [r["occupancy"] for r in rows] == [0.75, 1.0, 1.0]
    fleet.observe_tenant("t0", _record(fairness=[_qrow([1, 0, 0], [1, 0, 0])]))
    w = fleet.close_window()
    assert w.batches["launches"] == 3 and w.batches["padded_slots"] == 1
    assert w.batches["by_bucket"]["4"]["mean_occupancy"] == pytest.approx(0.875)
    # the new families render conformant prometheus text
    text = reg.render()
    check_promtext(text)
    for fam in ("fleet_windows_total", "fleet_tenant_share",
                "fleet_starvation_seconds", "pool_batch_occupancy",
                "pool_batch_padding_total", "pool_batch_launches_total"):
        assert f"kat_{fam}" in text, f"missing family {fam}"


# ---- shard rollups + skew alert ----


def test_shard_rollup_columns_and_skew_burn_alert():
    reg = MetricsRegistry(namespace="t")
    assert shard_rollup_values(reg) == {}  # never sharded: no columns
    reg.gauge_set("shard_skew", 0.8)
    reg.gauge_set("shard_valid_nodes", 12, labels={"shard": "0"})
    reg.gauge_set("shard_valid_nodes", 3, labels={"shard": "1"})
    reg.gauge_set("snapshot_shard_delta_rows", 7, labels={"shard": "1"})
    vals = shard_rollup_values(reg)
    assert vals == {
        "shard_skew": 0.8, "shard_valid_s0": 12.0, "shard_valid_s1": 3.0,
        "shard_dirty_s1": 7.0,
    }
    # the sampler folds the columns into its ring and the skew monitor
    # fires an SLO-burn-style alert once per episode
    clock = _Clock()
    flight = _Flight()
    ring = TimeSeriesRing(capacity=64, now_fn=clock)
    monitor = SkewBurnMonitor(
        ring, skew_slo=0.5, budget=0.5, windows=((40.0, 10.0, 1.5),),
        registry=reg, flight=flight, min_samples=4,
    )
    sampler = CycleSampler(ring=ring, registry=reg, skew_monitor=monitor)
    from kube_arbitrator_tpu.framework.scheduler import CycleStats

    stats = CycleStats(cycle_ms=5.0, snapshot_ms=1.0, binds=1, evicts=0,
                       pending_before=0)
    for i in range(8):
        clock.t += 2.0
        sampler.on_cycle(stats, ts=clock.t)
    assert ring.rows()[-1]["shard_skew"] == 0.8
    kinds = [k for k, _ in flight.anomalies]
    assert kinds.count("shard_skew") == 1, flight.anomalies
    assert reg.counter_value("shard_skew_alerts_total", {"window": "40s"}) == 1
    # hysteresis: balanced shards recover the short window, then a new
    # imbalance fires a new episode
    reg.gauge_set("shard_skew", 0.0)
    for i in range(8):
        clock.t += 2.0
        sampler.on_cycle(stats, ts=clock.t)
    reg.gauge_set("shard_skew", 0.9)
    for i in range(16):
        clock.t += 2.0
        sampler.on_cycle(stats, ts=clock.t)
    assert [k for k, _ in flight.anomalies].count("shard_skew") == 2


# ---- pool-batch trace stitching ----


def test_batch_trace_stitching_one_shared_span_k_links():
    """k batched tenants -> ONE shared pool_batch span under the minted
    batch_id, k links, and each tenant's chrome export renders the
    shared launch."""
    from kube_arbitrator_tpu.utils.tracing import Tracer

    tr = Tracer(enabled=True)
    import kube_arbitrator_tpu.utils.tracing as tracing_mod

    prev = tracing_mod._tracer
    tracing_mod._tracer = tr
    try:
        fleet = FleetPlane(registry=MetricsRegistry(namespace="t"))
        pool = DecisionPool(replicas=1, threaded=False, fleet=fleet)
        cfg = SchedulerConfig.default()
        reqs = []
        for i in range(3):
            sim = generate_cluster(num_nodes=16, num_jobs=4, tasks_per_job=4,
                                   num_queues=2, seed=700 + i)
            st = build_snapshot(sim.cluster).tensors
            reqs.append((f"t{i}", st, cfg, None, f"c{i:06d}-test"))
        out = pool.decide_many(reqs)
        assert all(r.error is None for r in out)
        batch_id = out[0].batch_id
        assert batch_id and all(r.batch_id == batch_id for r in out)
        # one shared batch span, correct schema
        spans = tr.spans(batch_id)
        assert len(spans) == 1 and spans[0].name == "pool_batch"
        args = spans[0].args
        assert args["size"] == 3 and args["bucket"] == 4
        assert args["replica"] == "r0" and args["compile"] == "compile"
        assert args["tenants"] == ["t0", "t1", "t2"]
        # k links, and every tenant's export includes the shared launch
        for i in range(3):
            corr = f"c{i:06d}-test"
            assert tr.links(corr) == [batch_id]
            names = [e["name"] for e in tr.export_chrome(corr)["traceEvents"]]
            assert "pool_batch" in names and "pool_batch_link" in names
        # the decision log joins by batch_id
        served = [e for e in pool.decision_log if e["outcome"] == "served"]
        assert all(e["batch_id"] == batch_id for e in served)
        # a second same-shape launch is a reuse
        out2 = pool.decide_many([r[:4] for r in reqs])
        spans2 = tr.spans(out2[0].batch_id)
        assert spans2[0].args["compile"] == "reuse"
    finally:
        tracing_mod._tracer = prev


# ---- flight digests (satellite: pool_outcomes + shard_skew) ----


def test_flight_digest_records_pool_outcomes_and_shard_skew():
    from kube_arbitrator_tpu.utils.metrics import metrics

    metrics().gauge_set("shard_skew", 0.125)
    fleet = FleetPlane()
    pool = DecisionPool(replicas=1, threaded=False, fleet=fleet)
    sim = generate_cluster(num_nodes=16, num_jobs=4, tasks_per_job=4,
                           num_queues=2, seed=810)
    flight = FlightRecorder(capacity=8)
    sched = Scheduler(sim, decider=PoolClient(pool, "t0"), arena=True,
                      flight=flight)
    sched.run(max_cycles=2, until_idle=False)
    rec = flight.last()
    assert rec.digests["shard_skew"] == 0.125
    # per-cycle DELTA: exactly one serve per cycle
    assert rec.digests["pool_outcomes"] == {"served": 1}


def test_flight_digest_pool_outcomes_empty_for_local_deciders():
    sim = generate_cluster(num_nodes=16, num_jobs=4, tasks_per_job=4,
                           num_queues=2, seed=811)
    flight = FlightRecorder(capacity=8)
    sched = Scheduler(sim, flight=flight)
    sched.run(max_cycles=1, until_idle=False)
    assert flight.last().digests["pool_outcomes"] == {}


# ---- the 2x4 threaded e2e reconciliation ----


def test_fleet_e2e_2x4_reconciles_with_per_tenant_audit_ledgers():
    """2 replicas x 4 tenant frontends on threads, each tenant with its
    own audit log; after the run the fleet window's totals must equal
    the sums of the per-tenant /debug/audit ledgers, the per-tenant
    served counts must equal the pool decision log, and /debug/fleet +
    /debug/fleet/tenants must serve the same numbers."""
    fleet = FleetPlane()
    pool = DecisionPool(replicas=2, threaded=True, min_fill=4,
                        batch_delay_s=0.25, max_batch=8, fleet=fleet)
    sims = [generate_cluster(num_nodes=16, num_jobs=4, tasks_per_job=4,
                             num_queues=2, seed=900 + i) for i in range(4)]
    audits = [AuditLog(capacity=16) for _ in range(4)]
    scheds = [
        Scheduler(s, decider=PoolClient(pool, f"t{i}"), arena=True,
                  audit=audits[i])
        for i, s in enumerate(sims)
    ]
    threads = [
        threading.Thread(target=lambda s=s: s.run(max_cycles=3, until_idle=False))
        for s in scheds
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    pool.close()
    for i, audit in enumerate(audits):
        rec = audit.last()
        assert rec is not None, f"tenant t{i} produced no audit record"
        fleet.observe_tenant(f"t{i}", rec)
    w = fleet.close_window()
    assert w.conservation["ok"], w.conservation
    by = {r["tenant"]: r for r in w.tenants}
    assert sorted(by) == ["t0", "t1", "t2", "t3"]
    # per-tenant serve counts reconcile 1:1 with the pool decision log
    for i in range(4):
        served_log = [
            e for e in pool.decision_log
            if e["tenant"] == f"t{i}" and e["outcome"] in ("served", "resent")
        ]
        row = by[f"t{i}"]
        assert row["served"] + row["resent"] == len(served_log) == 3
    # fleet totals == the sums of the per-tenant audit ledgers
    F = len(w.totals["capacity"])
    want_cap = [0.0] * F
    want_alloc = [0.0] * F
    for audit in audits:
        rec = audit.last().to_dict()
        for f in range(F):
            want_cap[f] += rec["cluster_total"][f]
        for qrow in rec["fairness"]:
            for f in range(min(F, len(qrow["allocated"]))):
                want_alloc[f] += qrow["allocated"][f]
    assert w.totals["capacity"] == pytest.approx(want_cap, abs=1e-2)
    assert w.totals["allocated"] == pytest.approx(want_alloc, abs=1e-2)
    # per-tenant realized = dominant share of the aggregate capacity
    for i, audit in enumerate(audits):
        rec = audit.last().to_dict()
        alloc = [0.0] * F
        for qrow in rec["fairness"]:
            for f in range(min(F, len(qrow["allocated"]))):
                alloc[f] += qrow["allocated"][f]
        want = max(
            (alloc[f] / want_cap[f] for f in range(F) if want_cap[f] > 0),
            default=0.0,
        )
        assert by[f"t{i}"]["realized"] == pytest.approx(want, abs=1e-4)
    # the served plane agrees with the in-memory join
    server, _t, url = serve_obs(fleet=fleet, pool=pool)
    try:
        fl = json.load(urllib.request.urlopen(url + "/debug/fleet", timeout=10))
        assert fl["window"]["totals"] == w.totals
        assert fl["windows_closed"] == 1
        assert fl["window"]["batches"]["launches"] >= 1
        tb = json.load(
            urllib.request.urlopen(url + "/debug/fleet/tenants", timeout=10)
        )
        assert {r["tenant"]: r for r in tb["tenants"]} == by
        assert tb["conservation"]["ok"]
    finally:
        server.shutdown()


def test_debug_fleet_unwired_returns_stub():
    server, _t, url = serve_obs()
    try:
        fl = json.load(urllib.request.urlopen(url + "/debug/fleet", timeout=10))
        assert "error" in fl and fl["tenants"] == []
    finally:
        server.shutdown()


# ---- the chaos canary ----


def test_fleet_ledger_chaos_canary_breaches():
    from kube_arbitrator_tpu.chaos.pool_runner import run_pool_chaos

    clean = run_pool_chaos(seed=3, cycles=4)
    assert not clean.breaches, clean.breaches
    mutated = run_pool_chaos(seed=3, cycles=4, disabled=("fleet-ledger",))
    kinds = {b.invariant for b in mutated.breaches}
    assert kinds == {"fleet_ledger_consistency"}, mutated.breaches
