"""One-to-one mirrors of the reference e2e suite (test/e2e/{job,predicates,
queue}.go) against the simulated cluster.

Each test carries the reference scenario name and file:line.  The 3-node
DinD cluster (hack/run-e2e.sh:6) becomes a 3-node sim; "waitPodGroupReady"
becomes gang-readiness after the scheduler loop settles; pod termination
after eviction (the kubelet's job in the reference) is simulated between
cycles by removing RELEASING tasks.
"""
import numpy as np
import pytest

from kube_arbitrator_tpu.api import TaskStatus
from kube_arbitrator_tpu.api.info import MatchExpression, PodAffinityTerm, Taint, Toleration
from kube_arbitrator_tpu.cache import SimCluster
from kube_arbitrator_tpu.framework import Scheduler
from kube_arbitrator_tpu.framework.conf import load_conf

GB = 1024**3
CPU = 1000  # oneCPU (util.go)

# the e2e run uses the full-action conf (example/kube-batch-conf.yaml)
FULL_CONF = load_conf(
    'actions: "reclaim, allocate, backfill, preempt"\n'
    "tiers:\n"
    "- plugins:\n"
    "  - name: priority\n"
    "  - name: gang\n"
    "- plugins:\n"
    "  - name: drf\n"
    "  - name: predicates\n"
    "  - name: proportion\n"
)

PLACED = (TaskStatus.ALLOCATED, TaskStatus.BINDING, TaskStatus.BOUND, TaskStatus.RUNNING)


def three_node_cluster(sim: SimCluster, cpu_milli: float = 4 * CPU):
    """NUM_NODES=3 DinD cluster analog; capacity 12 one-CPU slots."""
    for i in range(3):
        sim.add_node(f"node-{i}", cpu_milli=cpu_milli, memory=32 * GB)
    return 3 * int(cpu_milli // CPU)  # clusterSize(oneCPU)


def settle(sim, config=None, max_cycles=10) -> Scheduler:
    """Run scheduler cycles until quiescent, playing the kubelet between
    cycles: evicted (RELEASING) pods terminate and are deleted; bound pods
    start RUNNING."""
    sched = Scheduler(sim, config=config)
    for _ in range(max_cycles):
        result = sched.run_once()
        dying = [
            t
            for j in sim.cluster.jobs.values()
            for t in list(j.tasks.values())
            if t.status == TaskStatus.RELEASING
        ]
        for t in dying:
            if t.node_name:
                sim.cluster.nodes[t.node_name].remove_task(t)
            del sim.cluster.jobs[t.job_uid].tasks[t.uid]
        for j in sim.cluster.jobs.values():
            for t in j.tasks.values():
                if t.status == TaskStatus.BOUND:
                    node = sim.cluster.nodes[t.node_name]
                    node.remove_task(t)
                    t.status = TaskStatus.RUNNING
                    node.add_task(t)
        if not result.binds and not result.evicts and not dying:
            break
    return sched


def delete_job_and_pods(sim, job):
    """kubectl delete job: pods terminate, then the job object is GC'd."""
    for t in list(job.tasks.values()):
        if t.node_name:
            sim.cluster.nodes[t.node_name].remove_task(t)
        t.status = TaskStatus.SUCCEEDED
    sim.delete_job(job.uid)
    sim.collect_garbage(now=1e18)  # past the 5s GC delay


def ready_tasks(job) -> int:
    return sum(1 for t in job.tasks.values() if t.status in PLACED)


def gang_ready(job) -> bool:
    return ready_tasks(job) >= max(job.min_available, 1)


def make_job(sim, name, queue, rep, minm, cpu=CPU, mem=1 * GB, priority=1, **task_kw):
    j = sim.add_job(name, queue=queue, min_available=minm, creation_ts=float(len(sim.cluster.jobs)))
    for i in range(rep):
        sim.add_task(j, cpu, mem if cpu else 0, name=f"{name}-{i}", priority=priority, **task_kw)
    return j


def settle_with_controller(sim, config, max_cycles=20):
    """settle() plus the Job controller: evicted pods are recreated as new
    pending tasks of their job.  Returns per-cycle ready counts per job —
    the observable the e2e's polling waitTasksReady() sees."""
    sched = Scheduler(sim, config=config)
    history = {}
    for _ in range(max_cycles):
        result = sched.run_once()
        dying = [
            t
            for j in sim.cluster.jobs.values()
            for t in list(j.tasks.values())
            if t.status == TaskStatus.RELEASING
        ]
        for t in dying:
            if t.node_name:
                sim.cluster.nodes[t.node_name].remove_task(t)
            job = sim.cluster.jobs[t.job_uid]
            del job.tasks[t.uid]
            sim.add_task(job, t.resreq[0], t.resreq[1], name=f"{t.uid}.r", priority=t.priority)
        for j in sim.cluster.jobs.values():
            for t in j.tasks.values():
                if t.status == TaskStatus.BOUND:
                    node = sim.cluster.nodes[t.node_name]
                    node.remove_task(t)
                    t.status = TaskStatus.RUNNING
                    node.add_task(t)
        for j in sim.cluster.jobs.values():
            history.setdefault(j.uid, []).append(ready_tasks(j))
        if not result.binds and not result.evicts and not dying:
            break
    return history


def test_schedule_job():
    """job.go:27 'Schedule Job': one gang fits -> PodGroup ready."""
    sim = SimCluster()
    sim.add_queue("default")
    rep = three_node_cluster(sim)
    j = make_job(sim, "qj-1", "default", rep=2, minm=2)
    settle(sim)
    assert ready_tasks(j) == 2 and gang_ready(j)


def test_schedule_multiple_jobs():
    """job.go:48 'Schedule Multiple Jobs': three 2-replica gangs all run."""
    sim = SimCluster()
    sim.add_queue("default")
    three_node_cluster(sim)
    jobs = [make_job(sim, f"mqj-{i}", "default", rep=2, minm=2) for i in range(3)]
    settle(sim)
    assert all(gang_ready(j) for j in jobs)


def test_gang_scheduling_blocked_then_released():
    """job.go:82 'Gang scheduling': a gang needing rep/2+1 slots of a
    cluster whose free half is too small stays FULLY pending; deleting the
    filler job releases it."""
    sim = SimCluster()
    sim.add_queue("default")
    rep = three_node_cluster(sim)
    filler = sim.add_job("filler", queue="default", min_available=0, creation_ts=0)
    for i in range(rep // 2 + 1):  # occupy just over half
        sim.add_task(filler, CPU, 0, status=TaskStatus.RUNNING, node=f"node-{i % 3}", name=f"f{i}")
    gang = make_job(sim, "gang-qj", "default", rep=rep // 2 + 1, minm=rep // 2 + 1)
    settle(sim)
    assert ready_tasks(gang) == 0, "partial gang placement leaked"
    delete_job_and_pods(sim, filler)
    settle(sim)
    assert gang_ready(gang)


def test_gang_full_occupied():
    """job.go:118 'Gang scheduling: Full Occupied': gang 1 fills the
    cluster and stays ready; an identical gang 2 stays pending."""
    sim = SimCluster()
    sim.add_queue("default")
    rep = three_node_cluster(sim)
    j1 = make_job(sim, "gang-fq-qj1", "default", rep=rep, minm=rep)
    settle(sim)
    assert gang_ready(j1)
    j2 = make_job(sim, "gang-fq-qj2", "default", rep=rep, minm=rep)
    settle(sim, config=FULL_CONF)
    assert ready_tasks(j2) == 0
    assert ready_tasks(j1) == rep, "full-occupied gang must not be preempted"


def test_preemption():
    """job.go:149 'Preemption': a second job in the same queue preempts the
    first; with the e2e tiers gang (tier 1) alone filters victims — its
    non-nil verdict short-circuits DRF (session_plugins.go:131-135's
    nil-poisoning) — so each cycle drains the victim to its gang floor and
    the Job controller's recreated pods preempt back.  The e2e's polling
    waitTasksReady(pg, rep/2) observes each job at >= rep/2 at some point
    of that exchange; assert the same eventually-contract."""
    sim = SimCluster()
    sim.add_queue("default")
    rep = three_node_cluster(sim)
    j1 = make_job(sim, "preemptee-qj", "default", rep=rep, minm=1, mem=0)
    settle(sim)
    assert ready_tasks(j1) == rep
    j2 = make_job(sim, "preemptor-qj", "default", rep=rep, minm=1, mem=0)
    history = settle_with_controller(sim, FULL_CONF, max_cycles=8)
    # the preemptor attains its full fair half; the preemptee's observable
    # maximum is one task coarser because the sim's lockstep cycles
    # quantize the exchange (the live cluster's pod lifecycle interleaves)
    assert max(history[j2.uid]) >= rep // 2, history
    assert max(history[j1.uid]) >= rep // 2 - 1, history
    # invariants at every cycle: the victim job never drops below its gang
    # floor (gang.go:104-127), and total ready never exceeds capacity
    assert min(history[j1.uid]) >= j1.min_available, history
    for a, b in zip(history[j1.uid], history[j2.uid]):
        assert a + b <= rep


def test_multiple_preemption():
    """job.go:181 'Multiple Preemption': two preemptors arrive; every job
    attains >= rep/3 ready tasks (same eventually-contract as above)."""
    sim = SimCluster()
    sim.add_queue("default")
    rep = three_node_cluster(sim)
    j1 = make_job(sim, "preemptee-qj", "default", rep=rep, minm=1, mem=0)
    settle(sim)
    j2 = make_job(sim, "preemptor-qj1", "default", rep=rep, minm=1, mem=0)
    j3 = make_job(sim, "preemptor-qj2", "default", rep=rep, minm=1, mem=0)
    history = settle_with_controller(sim, FULL_CONF, max_cycles=12)
    # preemptors attain the full fair third; the original job's observable
    # max is one task coarser (lockstep quantization, as in
    # test_preemption); the victim never drops below its gang floor
    for j in (j2, j3):
        assert max(history[j.uid]) >= rep // 3, history
    assert max(history[j1.uid]) >= rep // 3 - 1, history
    assert min(history[j1.uid]) >= j1.min_available, history


def test_schedule_best_effort_job():
    """job.go:222 'Schedule BestEffort Job': a job mixing one-CPU tasks
    with zero-request (BestEffort) tasks gets both kinds placed."""
    sim = SimCluster()
    sim.add_queue("default")
    rep = three_node_cluster(sim)
    j = sim.add_job("best-effort-qj", queue="default", min_available=2, creation_ts=0)
    sim.add_task(j, CPU, 1 * GB, name="cpu-0")
    sim.add_task(j, CPU, 1 * GB, name="cpu-1")
    sim.add_task(j, 0, 0, name="be-0")
    sim.add_task(j, 0, 0, name="be-1")
    settle(sim)
    assert ready_tasks(j) == 4


def test_statement_no_spurious_evict():
    """job.go:252 'Statement': a preemptor gang too big to ever be ready
    must not leave any eviction behind (Commit only on JobReady)."""
    sim = SimCluster()
    sim.add_queue("default")
    rep = three_node_cluster(sim)
    j1 = make_job(sim, "st-qj-1", "default", rep=rep, minm=1)
    settle(sim)
    assert ready_tasks(j1) == rep
    # needs the whole cluster AND one more; can never be gang-ready
    make_job(sim, "st-qj-2", "default", rep=rep + 1, minm=rep + 1)
    sched = settle(sim, config=FULL_CONF)
    assert sum(s.evicts for s in sched.history) == 0
    assert ready_tasks(j1) == rep


def test_task_priority_within_job():
    """job.go:289 'TaskPriority': with room for only half the job, the
    high-priority (master) tasks win the slots."""
    sim = SimCluster()
    sim.add_queue("default")
    rep = three_node_cluster(sim)
    filler = sim.add_job("filler", queue="default", min_available=0, creation_ts=0)
    for i in range(rep // 2):
        sim.add_task(filler, CPU, 0, status=TaskStatus.RUNNING, node=f"node-{i % 3}", name=f"f{i}")
    j = sim.add_job("tp-qj", queue="default", min_available=1, creation_ts=1)
    for i in range(rep // 2):
        sim.add_task(j, CPU, 1 * GB, name=f"master-{i}", priority=100)
    for i in range(rep // 2):
        sim.add_task(j, CPU, 1 * GB, name=f"worker-{i}", priority=1)
    settle(sim)
    placed = {t.name for t in j.tasks.values() if t.status in PLACED}
    assert placed == {f"master-{i}" for i in range(rep // 2)}


def test_mixed_resource_requests_one_loop():
    """job.go:329 'Try to fit unassigned task with different resource
    requests in one loop': when the job's first (high-priority, 2-CPU)
    task cannot fit in the 1-CPU hole, the loop must still place the
    second (half-CPU) task; minMember=1 makes the group schedulable."""
    sim = SimCluster()
    sim.add_queue("default")
    rep = three_node_cluster(sim)
    rs = sim.add_job("rs-1", queue="default", min_available=0, creation_ts=0)
    for i in range(rep - 1):
        sim.add_task(rs, CPU, 0, status=TaskStatus.RUNNING, node=f"node-{i % 3}", name=f"rs{i}")
    j = sim.add_job("multi-task-diff-resource-job", queue="default", min_available=1, creation_ts=1)
    sim.add_task(j, 2 * CPU, 1 * GB, name="big-master", priority=100)
    sim.add_task(j, CPU // 2, 1 * GB, name="small-worker", priority=1)
    settle(sim)
    placed = {t.name for t in j.tasks.values() if t.status in PLACED}
    assert placed == {"small-worker"}


def test_node_affinity():
    """predicates.go:29 'NodeAffinity': required node-affinity pins every
    replica to the named node."""
    sim = SimCluster()
    sim.add_queue("default")
    for i in range(3):
        sim.add_node(f"node-{i}", cpu_milli=4 * CPU, memory=32 * GB, labels={"kubernetes.io/hostname": f"node-{i}"})
    j = sim.add_job("na-job", queue="default", min_available=1, creation_ts=0)
    expr = MatchExpression(key="kubernetes.io/hostname", operator="In", values=("node-2",))
    for i in range(2):
        sim.add_task(j, CPU, 1 * GB, name=f"na-{i}", node_affinity=(expr,))
    settle(sim)
    assert {t.node_name for t in j.tasks.values() if t.status in PLACED} == {"node-2"}


def test_hostport():
    """predicates.go:78 'Hostport': 2x replicas with one host port on a
    3-node cluster -> exactly one per node ready, the rest pending."""
    sim = SimCluster()
    sim.add_queue("default")
    nn = 3
    three_node_cluster(sim)
    j = sim.add_job("hp-job", queue="default", min_available=nn, creation_ts=0)
    for i in range(nn * 2):
        sim.add_task(j, CPU, 1 * GB, name=f"hp-{i}", host_ports=(28080,))
    settle(sim)
    placed = [t for t in j.tasks.values() if t.status in PLACED]
    assert len(placed) == nn
    assert len({t.node_name for t in placed}) == nn, "one port user per node"


def test_pod_affinity():
    """predicates.go:106 'Pod Affinity': a worker with required pod
    affinity to the master's label lands on the master's node."""
    sim = SimCluster()
    sim.add_queue("default")
    for i in range(3):
        sim.add_node(f"node-{i}", cpu_milli=4 * CPU, memory=32 * GB, labels={"kubernetes.io/hostname": f"node-{i}"})
    j = sim.add_job("pa-job", queue="default", min_available=2, creation_ts=0)
    sim.add_task(j, CPU, 1 * GB, name="master", labels={"role": "master"})
    term = PodAffinityTerm(match_labels=(("role", "master"),), topology_key="kubernetes.io/hostname")
    sim.add_task(j, CPU, 1 * GB, name="worker", affinity=(term,))
    settle(sim)
    by_name = {t.name: t for t in j.tasks.values()}
    assert by_name["master"].status in PLACED and by_name["worker"].status in PLACED
    assert by_name["master"].node_name == by_name["worker"].node_name


def test_taints_tolerations():
    """predicates.go:155 'Taints/Tolerations': tainting a node excludes
    it; a tolerating job may use it."""
    sim = SimCluster()
    sim.add_queue("default")
    taint = Taint(key="test-taint-key", value="test-taint-val", effect="NoSchedule")
    sim.add_node("node-0", cpu_milli=4 * CPU, memory=32 * GB, taints=(taint,))
    sim.add_node("node-1", cpu_milli=4 * CPU, memory=32 * GB)
    plain = make_job(sim, "tt-job", "default", rep=2, minm=1)
    settle(sim)
    assert {t.node_name for t in plain.tasks.values() if t.status in PLACED} == {"node-1"}
    tol = Toleration(key="test-taint-key", operator="Equal", value="test-taint-val", effect="NoSchedule")
    tolerant = make_job(sim, "tt-tol-job", "default", rep=8, minm=1, tolerations=(tol,))
    settle(sim)
    placed_nodes = {t.node_name for t in tolerant.tasks.values() if t.status in PLACED}
    assert "node-0" in placed_nodes, "toleration must admit the tainted node"


def test_reclaim_between_queues():
    """queue.go:27 'Reclaim': q2's job reclaims from q1 (both weight 1)
    until proportion's Overused gate stops it at q2's deserved share —
    the e2e tasks request CPU only, so the all-dimension overused check
    (proportion.go:188-193) fires exactly at the 50/50 split and the
    system is STABLE there (unlike preemption, which has no such gate)."""
    sim = SimCluster()
    sim.add_queue("q1", weight=1)
    sim.add_queue("q2", weight=1)
    rep = three_node_cluster(sim)
    j1 = make_job(sim, "q1-qj-1", "q1", rep=rep, minm=1, mem=0)
    settle(sim)
    # proportion caps a queue's deserved the moment ANY resource dimension
    # exceeds its request (helpers.Min at proportion.go:128), so a CPU-only
    # workload's queue meets at the half-CPU mark and q1 allocates only
    # rep/2 — the e2e only demands waitPodGroupReady (gang min), same here
    assert gang_ready(j1) and ready_tasks(j1) >= rep // 2
    j2 = make_job(sim, "q2-qj-2", "q2", rep=rep, minm=1, mem=0)
    history = settle_with_controller(sim, FULL_CONF, max_cycles=20)
    expected = rep // 2 - 1  # one task of boundary churn (see below)
    assert history[j2.uid][-1] >= expected, history
    assert history[j1.uid][-1] >= expected, history
    # Invariant (every cycle once both queues are active): neither queue
    # drops below deserved minus the one marginal task the reclaim/allocate
    # exchange churns at the boundary — the reference's own
    # evict-then-"corrected in next scheduling loop" steady state.  And the
    # two queues never oversubscribe the cluster.
    for a, b in zip(history[j1.uid][1:], history[j2.uid][1:]):
        assert a >= expected and b >= expected, history
        assert a + b <= rep


def test_taint_untaint_node_mid_run():
    """util.go:746-800 (taintAllNodes / removeTaintsFromAllNodes): taints
    applied BETWEEN cycles redirect subsequent scheduling away from the
    tainted node; removing the taint restores it.  Running pods stay (the
    taint effect is NoSchedule)."""
    sim = SimCluster()
    sim.add_queue("default")
    three_node_cluster(sim)
    j1 = make_job(sim, "warm", "default", rep=3, minm=3)
    settle(sim, config=FULL_CONF)
    assert gang_ready(j1)

    # taint node-2 mid-run (strategic-merge patch analog)
    taint = Taint(key="test-taint-key", value="taint-val", effect="NoSchedule")
    sim.cluster.nodes["node-2"].taints.append(taint)
    j2 = make_job(sim, "after-taint", "default", rep=6, minm=1)
    settle(sim, config=FULL_CONF)
    placed_nodes = {t.node_name for t in j2.tasks.values() if t.status in PLACED}
    assert placed_nodes and "node-2" not in placed_nodes

    # untaint: the remaining pending tasks reach node-2 on the next cycles
    sim.cluster.nodes["node-2"].taints.clear()
    j3 = make_job(sim, "after-untaint", "default", rep=3, minm=1)
    settle(sim, config=FULL_CONF)
    placed3 = {t.node_name for t in j3.tasks.values() if t.status in PLACED}
    assert "node-2" in placed3


def test_eviction_detected_via_events():
    """util.go:419-438 waitTasksEvicted detects preemption through Evict
    EVENTS, not pod polling: the victim pods' eviction must surface on the
    event channel with their uids."""
    sim = SimCluster()
    sim.add_queue("default")
    rep = three_node_cluster(sim)
    j1 = make_job(sim, "victim-job", "default", rep=rep, minm=1, mem=0)
    settle(sim)
    assert ready_tasks(j1) == rep
    make_job(sim, "preemptor-job", "default", rep=rep, minm=1, mem=0)
    settle_with_controller(sim, FULL_CONF, max_cycles=6)

    evict_events = [e for e in sim.events if e.kind == "Evict"]
    assert evict_events, "no Evict events recorded"
    # the preempt/recreate exchange may also evict recreated preemptor
    # pods in later cycles; the victim job's evictions must be observable
    assert any(e.object_uid.startswith("victim-job") for e in evict_events)


def test_capacity_tight_queue_mix_matches_oracle():
    """Round-4 north-star shortfall pin (verdict #4): when a queue's
    proportion deserved binds BEFORE its demand, the batched kernel must
    place the same task count as the sequential loop — the per-queue
    DRF equilibrium levels keep the cohort's share growth in lockstep, so
    the queue's overused gate closes on the same task mix instead of one
    big-task job eating the deserved headroom (proportion.go:102-144 +
    allocate.go:71-74 check-before-pop semantics).

    Construction: queue "small" is weight-capped far below its demand and
    holds one big-task job and one small-task job of equal priority.  An
    unconstrained interleave fills the cap with a balanced mix; a
    first-selected-job jump would fill it with big tasks only and place
    strictly fewer."""
    from kube_arbitrator_tpu.cache import build_snapshot
    from kube_arbitrator_tpu.oracle import SequentialScheduler
    from kube_arbitrator_tpu.ops import schedule_cycle

    def build():
        sim = SimCluster()
        sim.add_queue("small", weight=1)
        sim.add_queue("hungry", weight=9)
        for i in range(12):
            sim.add_node(f"n{i}", cpu_milli=10_000, memory=20 * GB)
        jb = sim.add_job("big", queue="small", min_available=1)
        for i in range(20):
            sim.add_task(jb, 2000, 1 * GB, name=f"big-{i:02d}")
        js = sim.add_job("small", queue="small", min_available=1)
        for i in range(40):
            sim.add_task(js, 500, 4 * GB, name=f"small-{i:02d}")
        jh = sim.add_job("hog", queue="hungry", min_available=1)
        for i in range(80):
            sim.add_task(jh, 1000, 1 * GB, name=f"hog-{i:02d}")
        return sim

    sim_k = build()
    snap = build_snapshot(sim_k.cluster)
    dec = schedule_cycle(snap.tensors, actions=("allocate", "backfill"))
    kernel_placed = int(np.asarray(dec.bind_mask).sum())

    sim_o = build()
    res = SequentialScheduler(sim_o.cluster).run_cycle()
    oracle_placed = len(res.binds)

    # Equivalence doctrine (SURVEY §7 hard parts): allocate batches are
    # invariant-equivalent, not bind-for-bind — the residual delta on
    # this adversarial mix is bind-ORDER fragmentation (the oracle's
    # task-level interleave packs big and small tasks side by side; the
    # kernel's per-turn batches place each job's chunk contiguously, so
    # node-local cpu/mem leftovers differ).  The per-queue equilibrium
    # levels bound the delta to a few tasks; before them the first-served
    # job ate the whole deserved headroom (round-3: 102 of 112 here,
    # 99,600/100,000 at the north star; after: >=105 and 99,989).
    assert oracle_placed == 112, "oracle baseline moved; re-derive the envelope"
    assert kernel_placed >= 102, (
        f"kernel {kernel_placed} regressed below the pinned envelope "
        f"(oracle {oracle_placed})"
    )
    # every unplaced task is held back legitimately: its queue ended
    # overused, or no valid node can fit it (fragmentation)
    import jax

    from kube_arbitrator_tpu.ops.cycle import open_session
    from kube_arbitrator_tpu.ops.fairness import overused
    from kube_arbitrator_tpu.ops.ordering import DEFAULT_TIERS

    st = snap.tensors
    sess, _ = jax.jit(lambda s: open_session(s, DEFAULT_TIERS))(st)
    bm = np.asarray(dec.bind_mask)
    pending = (np.asarray(st.task_status) == 0) & np.asarray(st.task_valid)
    unplaced = pending & ~bm
    assert unplaced.any()
    rr = np.asarray(st.task_resreq)
    tj = np.asarray(st.task_job)
    jq = np.asarray(st.job_queue)
    alloc = np.zeros((st.num_queues, rr.shape[1]), np.float32)
    np.add.at(alloc, jq[tj[bm]], rr[bm])
    ov = np.asarray(overused(alloc, np.asarray(sess.deserved)))
    idle = np.asarray(dec.node_idle)
    valid = np.asarray(st.node_valid)
    for t in np.nonzero(unplaced)[0]:
        q_over = ov[jq[tj[t]]]
        fits = ((rr[t][None, :] < idle + 10.0).all(-1) & valid).any()
        assert q_over or not fits, f"task {t} strandable: queue open and a node fits"


def test_north_star_shaped_shortfall_is_pinned():
    """Round-5 directive #5: pin the north-star placement shortfall with
    its mechanism.

    At the north-star config (100k x 10k, 8 queues, seed 42) the kernel
    places 99,989/100,000 where the compiled C++ loop places 100,000 —
    but the C++ baseline implements NO proportion semantics.  The
    faithful comparator is the sequential oracle, and this test runs the
    same generator at 1/10 scale (same job/queue mix, same 8-core
    crossing signature): the oracle itself strands 1 task (proportion's
    check-before-pop closes the queue at its deserved boundary — faithful
    stopping, not a capacity bug; feasible nodes remain but the queue is
    legitimately overused) and the kernel strands exactly ONE more
    (99,98x pattern): at the final overused boundary the batched
    first-crossing clamp rounds one task more conservatively than the
    per-pop re-sorting interleave.  The deviation is bounded at one task
    per queue-crossing signature and is strictly conservative — the
    kernel never OVER-places past deserved (asserted here via the
    all-dims overused check).
    """
    from kube_arbitrator_tpu.cache import build_snapshot, generate_cluster
    from kube_arbitrator_tpu.framework.conf import SchedulerConfig
    from kube_arbitrator_tpu.ops import schedule_cycle
    from kube_arbitrator_tpu.ops.cycle import open_session

    sim = generate_cluster(num_nodes=1000, num_jobs=100, tasks_per_job=100,
                           num_queues=8, seed=42)
    snap = build_snapshot(sim.cluster)
    st = snap.tensors
    dec = schedule_cycle(st, actions=("allocate", "backfill"))
    placed = int(np.asarray(dec.bind_mask).sum())
    # oracle (measured once, deterministic seed): 9,999; kernel must stay
    # within ONE task of it and never regress below the pinned count
    assert placed == 9998, (
        f"kernel placed {placed}/10000 — the pinned boundary-rounding "
        "delta is oracle-1 == 9998; a lower count is a regression, a "
        "higher one means the first-crossing clamp changed (re-derive "
        "the pin against the oracle)"
    )

    # conservativeness: no queue's allocation may exceed its deserved in
    # ALL fair dims by more than the final check-before-pop grant (the
    # overused gate's own epsilon) — i.e. at most one crossing task per
    # queue past the boundary in the LAST-crossed dim
    import jax

    tiers = SchedulerConfig.default().tiers
    sess, _ = jax.jit(lambda s: open_session(s, tiers))(st)
    des = np.asarray(sess.deserved)[:, :3]
    bind = np.asarray(dec.bind_mask)
    tj = np.asarray(st.task_job)
    jq = np.asarray(st.job_queue)
    trr = np.asarray(st.task_resreq)
    qalloc = np.zeros((st.num_queues, trr.shape[1]))
    for t in np.nonzero(bind)[0]:
        qalloc[jq[tj[t]]] += trr[t]
    max_req = trr[np.asarray(st.task_valid)].max(axis=0)[:3]
    for q in range(int(np.asarray(st.n_valid_queues))):
        # alloc may exceed deserved in dims that crossed while another dim
        # was still under (the reference's all-dims OverusedFn), but the
        # LAST-crossed dim overshoots by at most one task's request
        overshoot = qalloc[q][:3] - des[q]
        assert (overshoot <= max_req + 10.0).any(), (
            f"queue {q} overshot deserved in every dim by more than one "
            f"task: {overshoot}"
        )


def test_full_actions_mid_panel_scale_vs_oracle():
    """Production-scale guard for the r5 three-tier victim panel: a
    full-action cycle big enough (T~8.7k) that preempt_action's switch
    takes the MIDDLE tier — asserted via the product's own gate — must
    stay invariant-clean and land within the documented
    invariant-equivalence window of the sequential oracle (SURVEY §7:
    valid schedules may fragment differently; bit-parity is pinned
    separately by test_panel_mid_tier_matches_full).  Measured on seeds
    0-3: kernel readiness >= oracle - 1 with <= 6/104 bidirectional
    mismatches; a panel-truncation regression (dropped victims) would
    collapse evictions and readiness far outside these bounds."""
    import jax

    from kube_arbitrator_tpu.cache import build_snapshot, generate_cluster
    from kube_arbitrator_tpu.ops import schedule_cycle
    from kube_arbitrator_tpu.ops.cycle import open_session
    from kube_arbitrator_tpu.ops.preempt import RUNNING, _entry_qualify
    from kube_arbitrator_tpu.oracle import SequentialScheduler

    GB = 1024 ** 3
    full = ("reclaim", "allocate", "backfill", "preempt")
    sim = generate_cluster(
        num_nodes=600,
        num_jobs=104,
        tasks_per_job=80,
        num_queues=24,
        seed=2,
        node_cpu_milli=8000,
        node_memory=16 * GB,
        running_fraction=0.35,
    )
    snap = build_snapshot(sim.cluster)
    st = snap.tensors

    # the production panel switch must take the MIDDLE tier for this
    # workload, or the test stops guarding what it exists to guard.  The
    # switch evaluates the qualify count at PREEMPT ENTRY — after
    # reclaim/allocate/backfill have shrunk the running pool — so the
    # gate is asserted on that state, not on session open (review catch;
    # measured: 1374-1624 qualifying at entry across seeds 0-3 vs the
    # 1088/2176 tier bounds).
    from kube_arbitrator_tpu.ops.cycle import ACTION_KERNELS
    from kube_arbitrator_tpu.ops.ordering import DEFAULT_TIERS

    # same tiers object the schedule_cycle default uses, so the gate is
    # computed under exactly the plugin semantics of the cycle under test
    tiers = DEFAULT_TIERS

    @jax.jit
    def entry_count(st):
        import jax.numpy as jnp

        sess, state = open_session(st, tiers)
        for a in ("reclaim", "allocate", "backfill"):
            state = ACTION_KERNELS[a](
                st, sess, state, tiers, s_max=4096, max_rounds=100_000
            )
        running0 = (
            (state.task_status == RUNNING) & st.task_valid & (state.task_node >= 0)
        )
        return jnp.sum(_entry_qualify(st, sess, state, running0).astype(jnp.int32))

    count = int(entry_count(st))
    T = st.num_tasks
    # the tier switch only exists at T//8 >= panel_floor (default 1024,
    # preempt_action) — below it the action takes the single full-width
    # path and this test would guard nothing
    assert T // 8 >= 1024, f"padded T={T} too small for the panel switch"
    assert T // 8 < count <= T // 4, (count, T // 8, T // 4)

    dec = schedule_cycle(st, actions=full)

    # invariants: no oversubscription; evictions only of running tasks;
    # every committed bind carries a node
    assert (np.asarray(dec.node_idle) > -1e-3).all()
    em = np.asarray(dec.evict_mask)
    assert em.sum() > 0, "no evictions — the victim path did not run"
    assert (np.asarray(st.task_status)[em] == int(RUNNING)).all()
    bm = np.asarray(dec.bind_mask)
    assert bm.sum() > 0
    assert (np.asarray(dec.task_node)[bm] >= 0).all()

    oracle = SequentialScheduler(sim.cluster).run_cycle(actions=full)
    jr = np.asarray(dec.job_ready)
    job_ready_k = {j.uid: bool(jr[j.ordinal]) for j in snap.index.jobs}
    mismatch = sum(
        1 for u, v in job_ready_k.items() if v != oracle.job_ready.get(u, False)
    )
    n_ready_k = sum(job_ready_k.values())
    n_ready_o = sum(oracle.job_ready.values())
    assert n_ready_k >= n_ready_o - 1, (n_ready_k, n_ready_o)
    assert mismatch <= 10, f"{mismatch} gang-readiness mismatches vs oracle"
    n_binds = int(bm.sum())
    assert abs(n_binds - len(oracle.binds)) <= max(40, len(oracle.binds) // 5), (
        n_binds, len(oracle.binds)
    )
