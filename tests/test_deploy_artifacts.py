"""Deployment artifacts: the CRD schemas are the wire contract the live
plane consumes (reference config/crds/*.yaml + deployment/kube-batch)."""
import os

import yaml

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(rel):
    with open(os.path.join(HERE, rel)) as f:
        return list(yaml.safe_load_all(f))


def test_podgroup_crd_matches_live_plane_contract():
    (crd,) = _load("deploy/crds/scheduling_v1alpha1_podgroup.yaml")
    assert crd["spec"]["group"] == "scheduling.incubator.k8s.io"
    assert crd["spec"]["names"]["kind"] == "PodGroup"
    ver = crd["spec"]["versions"][0]
    assert ver["name"] == "v1alpha1" and ver["storage"]
    props = ver["schema"]["openAPIV3Schema"]["properties"]
    # exactly the fields cache/live.py reads and writes back
    assert set(props["spec"]["properties"]) >= {"minMember", "queue"}
    st = props["status"]["properties"]
    assert set(st) >= {"phase", "running", "succeeded", "failed", "conditions"}
    assert st["phase"]["enum"] == ["Pending", "Running", "Unknown"]
    assert "status" in ver["subresources"]  # the PUT /status verb


def test_queue_crd_contract():
    (crd,) = _load("deploy/crds/scheduling_v1alpha1_queue.yaml")
    assert crd["spec"]["names"]["kind"] == "Queue"
    assert crd["spec"]["scope"] == "Cluster"  # cluster-scoped (types.go:152)
    props = crd["spec"]["versions"][0]["schema"]["openAPIV3Schema"]["properties"]
    assert "weight" in props["spec"]["properties"]


def test_deployment_manifests_carry_full_conf():
    docs = _load("deploy/kube-arbitrator-tpu.yaml")
    kinds = {d["kind"] for d in docs}
    assert kinds == {"ServiceAccount", "ClusterRoleBinding", "ConfigMap", "Deployment"}
    cm = next(d for d in docs if d["kind"] == "ConfigMap")
    conf = cm["data"]["scheduler.conf"]
    # the conf must parse through the real loader with all four actions
    from kube_arbitrator_tpu.framework.conf import load_conf

    cfg = load_conf(conf)
    assert cfg.actions == ("reclaim", "allocate", "backfill", "preempt")
