"""Self-tests for the first-party static analyzer
(``kube_arbitrator_tpu.analysis``): one violating + one clean fixture per
rule family, CLI exit-code contract, and the integration gate asserting
the real tree is clean.
"""
import pathlib
import subprocess
import sys
import textwrap

import pytest

from kube_arbitrator_tpu.analysis import ALL_RULES, analyze_paths

REPO = pathlib.Path(__file__).resolve().parents[1]


def run_on(tmp_path, name, source, rules=ALL_RULES):
    f = tmp_path / name
    f.write_text(textwrap.dedent(source))
    _, findings = analyze_paths([str(f)], rules)
    return findings


def rule_ids(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# KAT-SYN — syntax gate


def test_syn_flags_py312_only_fstring(tmp_path):
    # the exact seed regression: backslash escape inside the f-string
    # EXPRESSION part (format specs allow them; expressions do not pre-3.12)
    src = 'x = "a"\ny = f"{x + \'\\\\n\'}"\n'
    if sys.version_info >= (3, 12):
        pytest.skip("3.12+ parses backslashes in f-string expressions")
    findings = run_on(tmp_path, "bad.py", src)
    assert rule_ids(findings) == {"KAT-SYN-001"}
    assert findings[0].line == 2
    assert findings[0].severity == "error"


def test_syn_clean_module_passes(tmp_path):
    findings = run_on(tmp_path, "ok.py", 'x = 1\ny = f"{x}"\n')
    assert findings == []


# ---------------------------------------------------------------------------
# KAT-TRC — tracer hygiene


def test_trc_flags_control_flow_and_concretization(tmp_path):
    findings = run_on(
        tmp_path,
        "kern.py",
        """
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def kern(x):
            if jnp.sum(x) > 0:          # TRC-001
                x = x + 1
            n = int(jnp.max(x))          # TRC-002
            y = np.argsort(jnp.abs(x))   # TRC-003
            return x * n + y
        """,
    )
    assert rule_ids(findings) == {"KAT-TRC-001", "KAT-TRC-002", "KAT-TRC-003"}


def test_trc_static_branches_and_metadata_are_clean(tmp_path):
    # static unrolls and dtype-metadata checks are the repo's idiom
    findings = run_on(
        tmp_path,
        "kern.py",
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kern(x, native_ops=False, actions=("allocate",)):
            if native_ops:                      # static flag: legal
                x = x * 2
            for a in actions:                   # static unroll: legal
                x = x + len(a)
            if jnp.issubdtype(x.dtype, jnp.floating):  # metadata: legal
                x = x.astype(jnp.float32)
            return jnp.where(x > 0, x, 0)
        """,
    )
    assert findings == []


def test_trc_applies_to_action_kernel_registry_and_helpers(tmp_path):
    # undecorated, but registered in ACTION_KERNELS and calling a
    # same-module helper: both are kernel context
    findings = run_on(
        tmp_path,
        "ops.py",
        """
        import jax.numpy as jnp

        def _helper(x):
            while jnp.any(x > 0):   # TRC-001, via closure
                x = x - 1
            return x

        def my_action(st):
            return _helper(st)

        ACTION_KERNELS = {"my": my_action}
        """,
    )
    assert rule_ids(findings) == {"KAT-TRC-001"}


# ---------------------------------------------------------------------------
# KAT-PUR — purity


def test_pur_flags_mutation_of_snapshot_and_captured_state(tmp_path):
    findings = run_on(
        tmp_path,
        "kern.py",
        """
        import jax

        SEEN = []

        @jax.jit
        def kern(st, x):
            st.weights[0] = 1.0     # PUR-001
            st.total += 2.0         # PUR-002
            SEEN.append(1)          # PUR-003
            x.at[0].set(5.0)        # PUR-004 (discarded update)
            return x
        """,
    )
    assert rule_ids(findings) == {
        "KAT-PUR-001", "KAT-PUR-002", "KAT-PUR-003", "KAT-PUR-004",
    }


def test_pur_local_accumulators_and_bound_at_updates_are_clean(tmp_path):
    findings = run_on(
        tmp_path,
        "kern.py",
        """
        import jax

        @jax.jit
        def kern(x):
            keys = []
            keys.append(x)            # local static unroll: legal
            x = x.at[0].set(5.0)      # bound functional update: legal
            total = 0.0
            total += 1.0              # local scalar: legal
            return x, keys, total
        """,
    )
    assert findings == []


# ---------------------------------------------------------------------------
# KAT-RTR — retrace hazards


def test_rtr_flags_per_call_jit_and_dynamic_statics(tmp_path):
    findings = run_on(
        tmp_path,
        "mod.py",
        """
        import jax

        def percycle(f, x, names):
            return jax.jit(f, static_argnames=names)(x)   # RTR-001 + RTR-002

        def factory(scale):
            @jax.jit
            def inner(x):
                return x * scale                          # RTR-003
            return inner
        """,
    )
    assert rule_ids(findings) == {"KAT-RTR-001", "KAT-RTR-002", "KAT-RTR-003"}


def test_rtr_module_level_literal_statics_are_clean(tmp_path):
    findings = run_on(
        tmp_path,
        "mod.py",
        """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("tiers", "native_ops"))
        def schedule(st, tiers=(), native_ops=False):
            return st
        """,
    )
    assert findings == []


def test_rtr_skips_test_files(tmp_path):
    # tests wrap ad-hoc one-shot jits deliberately
    findings = run_on(
        tmp_path,
        "test_mod.py",
        """
        import jax

        def test_thing():
            out = jax.jit(lambda s: s + 1)(1.0)
            assert out == 2.0
        """,
    )
    assert findings == []


# ---------------------------------------------------------------------------
# KAT-DRF — config drift


def test_drf_flags_resolve_without_decision_device(tmp_path):
    findings = run_on(
        tmp_path,
        "sidecar.py",
        """
        from kube_arbitrator_tpu.platform import resolve_native_ops

        def decide(st, schedule_cycle):
            return schedule_cycle(st, native_ops=resolve_native_ops())
        """,
    )
    assert rule_ids(findings) == {"KAT-DRF-001"}


def test_drf_flags_hardcoded_native_ops_literal(tmp_path):
    findings = run_on(
        tmp_path,
        "entry.py",
        """
        def decide(st, schedule_cycle):
            return schedule_cycle(st, native_ops=True)
        """,
    )
    assert rule_ids(findings) == {"KAT-DRF-002"}


def test_drf_clean_when_routed_through_the_seam(tmp_path):
    findings = run_on(
        tmp_path,
        "decider.py",
        """
        import contextlib
        import jax
        from kube_arbitrator_tpu.platform import decision_device, resolve_native_ops

        def decide(st, schedule_cycle, evictive=False):
            dev = decision_device(int(st.task_valid.shape[0]), evictive=evictive)
            ctx = jax.default_device(dev) if dev is not None else contextlib.nullcontext()
            with ctx:
                return schedule_cycle(st, native_ops=resolve_native_ops(dev))
        """,
    )
    assert findings == []


# ---------------------------------------------------------------------------
# KAT-DTY — dtype promotion discipline


def test_dty_flags_f64_constant_default_and_literal(tmp_path):
    findings = run_on(
        tmp_path,
        "kern.py",
        """
        import jax
        import numpy as np

        SCALE = np.array([1.0, 2.0])          # float64 by default

        @jax.jit
        def kern(x, eps=np.float64(10.0)):     # DTY-001 (default)
            y = x * SCALE                      # DTY-001 (module constant)
            z = np.zeros(4)                    # DTY-001 (f64 in body)
            return y + z + eps
        """,
    )
    assert rule_ids(findings) == {"KAT-DTY-001"}
    assert len(findings) == 3


def test_dty_flags_bool_arithmetic_and_x64_literals(tmp_path):
    findings = run_on(
        tmp_path,
        "kern.py",
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kern(x):
            n = (x > 0) * 3              # DTY-002
            big = jnp.where(x > 1e39, 0.0, x)   # DTY-003 (inf when f32)
            wide = x + 4_000_000_000     # DTY-003 (int32 overflow)
            return n + big + wide
        """,
    )
    assert rule_ids(findings) == {"KAT-DTY-002", "KAT-DTY-003"}
    assert sum(1 for f in findings if f.rule == "KAT-DTY-003") == 2


def test_dty_explicit_casts_and_host_constants_are_clean(tmp_path):
    # the repo idiom: explicit dtypes at the boundary, f64 module math
    # that never enters a kernel, masks cast before arithmetic
    findings = run_on(
        tmp_path,
        "mod.py",
        """
        import jax
        import jax.numpy as jnp
        import numpy as np

        HOST_SCALE = np.array([1.0, 2.0])      # f64, host-side only
        DEV_SCALE = np.array([1.0, 2.0], dtype=np.float32)

        def to_device_units(v):
            return (v * HOST_SCALE).astype(np.float32)

        @jax.jit
        def kern(x, mask):
            counted = mask.astype(jnp.int32) * 3
            y = x * DEV_SCALE
            return jnp.where(y > 3.0e38, 0.0, y) + counted
        """,
    )
    assert findings == []


# ---------------------------------------------------------------------------
# KAT-LCK — lock discipline


def test_lck_flags_bare_read_of_guarded_field(tmp_path):
    findings = run_on(
        tmp_path,
        "svc.py",
        """
        import threading

        class Service:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def bump(self):
                with self._lock:
                    self.count += 1

            def peek(self):
                return self.count        # LCK-001: bare read
        """,
    )
    assert rule_ids(findings) == {"KAT-LCK-001"}
    assert "peek" in findings[0].message


def test_lck_flags_blocking_call_under_lock(tmp_path):
    findings = run_on(
        tmp_path,
        "svc.py",
        """
        import threading

        class Service:
            def __init__(self):
                self._lock = threading.Lock()
                self.last = None

            def decide(self, dec):
                with self._lock:
                    dec.task_node.block_until_ready()   # LCK-002
                    self.last = dec
        """,
    )
    assert rule_ids(findings) == {"KAT-LCK-002"}
    assert "block_until_ready" in findings[0].message


def test_lck_disciplined_class_and_locked_helpers_are_clean(tmp_path):
    findings = run_on(
        tmp_path,
        "svc.py",
        """
        import threading

        class Service:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def bump(self):
                with self._lock:
                    self.count += 1
                    self._note_locked()

            def _note_locked(self):
                self.count += 0          # caller holds the lock

            def snapshot(self):
                with self._lock:
                    n = self.count
                # blocking work OUTSIDE the critical section is the idiom
                import time
                time.sleep(0)
                return n
        """,
    )
    assert findings == []


def test_lck_module_level_lock_blocking_call(tmp_path):
    findings = run_on(
        tmp_path,
        "handler.py",
        """
        import threading
        import urllib.request

        def route(server, req):
            lock = server.api_lock
            with lock:
                return urllib.request.urlopen(req)   # LCK-002
        """,
    )
    assert rule_ids(findings) == {"KAT-LCK-002"}


def test_lck_skips_test_files(tmp_path):
    findings = run_on(
        tmp_path,
        "test_threads.py",
        """
        import threading

        class Probe:
            def __init__(self):
                self._lock = threading.Lock()
                self.seen = 0

            def poke(self):
                with self._lock:
                    self.seen += 1

            def check(self):
                return self.seen
        """,
    )
    assert findings == []


# ---------------------------------------------------------------------------
# KAT-LCK-ORDER / KAT-LCK-BLOCK — the project-wide lock-order graph


def lock_graph_run(tmp_path, sources):
    from kube_arbitrator_tpu.analysis.core import load_project
    from kube_arbitrator_tpu.analysis.rules.lockorder import lock_order_findings

    for name, src in sources.items():
        (tmp_path / name).write_text(textwrap.dedent(src))
    return lock_order_findings(load_project([str(tmp_path)]))


CYCLE_FWD = """
    from kube_arbitrator_tpu.utils import locking

    LOCK_A = locking.Lock("fix.a")
    LOCK_B = locking.Lock("fix.b")

    def forward():
        with LOCK_A:
            with LOCK_B:
                pass
"""


def test_lck_order_flags_cross_module_cycle(tmp_path):
    findings = lock_graph_run(tmp_path, {
        "m1.py": CYCLE_FWD,
        "m2.py": """
            from m1 import LOCK_A, LOCK_B

            def backward():
                with LOCK_B:
                    with LOCK_A:
                        pass
        """,
    })
    assert rule_ids(findings) == {"KAT-LCK-ORDER"}
    assert len(findings) == 1 and findings[0].severity == "error"
    # the join-key names and both hop sites appear in the message
    assert "fix.a" in findings[0].message and "fix.b" in findings[0].message
    assert "m1.py" in findings[0].message and "m2.py" in findings[0].message


def test_lck_order_consistent_global_order_is_clean(tmp_path):
    findings = lock_graph_run(tmp_path, {
        "m1.py": CYCLE_FWD,
        "m2.py": """
            from m1 import LOCK_A, LOCK_B

            def also_forward():
                with LOCK_A:
                    with LOCK_B:
                        pass
        """,
    })
    assert findings == []


def test_lck_block_flags_queue_wait_under_lock(tmp_path):
    findings = lock_graph_run(tmp_path, {
        "w.py": """
            import queue
            import threading

            class Worker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.inbox = queue.Queue()

                def drain(self, fut):
                    with self._lock:
                        item = self.inbox.get()     # parks under the lock
                        return fut.result(), item   # so does the future
        """,
    })
    assert rule_ids(findings) == {"KAT-LCK-BLOCK"}
    assert len(findings) == 2
    assert all(f.severity == "warning" for f in findings)
    assert any("`get`" in f.message for f in findings)


def test_lck_block_condition_wait_on_held_lock_is_exempt(tmp_path):
    findings = lock_graph_run(tmp_path, {
        "g.py": """
            import threading

            class Gate:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._cond = threading.Condition(self._lock)

                def await_ready(self):
                    with self._cond:
                        self._cond.wait()   # releases the held lock: fine
        """,
    })
    assert findings == []


def test_lck_order_cli_gate(tmp_path):
    (tmp_path / "m1.py").write_text(textwrap.dedent(CYCLE_FWD))
    (tmp_path / "m2.py").write_text(textwrap.dedent("""
        from m1 import LOCK_A, LOCK_B

        def backward():
            with LOCK_B:
                with LOCK_A:
                    pass
    """))
    r = subprocess.run(
        [sys.executable, "-m", "kube_arbitrator_tpu.analysis",
         "--no-cache", "--rules", "KAT-LCK", str(tmp_path)],
        cwd=REPO, capture_output=True, text=True,
    )
    assert r.returncode == 1, r.stdout + r.stderr
    assert "KAT-LCK-ORDER" in r.stdout


def test_real_tree_lock_graph_has_named_nodes_and_no_cycles():
    from kube_arbitrator_tpu.analysis.core import load_project
    from kube_arbitrator_tpu.analysis.rules.lockorder import (
        build_lock_graph, lock_order_findings,
    )

    project = load_project([str(REPO / "kube_arbitrator_tpu")])
    graph = build_lock_graph(project)
    # the literal names are the join key with the runtime witness
    for name in ("pool.lock", "fleet.lock", "httpapi.api_lock"):
        assert name in graph.nodes, sorted(graph.nodes)
    orders = [f for f in lock_order_findings(project)
              if f.rule == "KAT-LCK-ORDER"]
    assert orders == [], "\n".join(f.format() for f in orders)


# ---------------------------------------------------------------------------
# integration: the real tree is clean, and the CLI contract holds


def test_real_tree_is_clean():
    # clean modulo the committed baseline — currently EMPTY: the last
    # justified KAT-EFF-001 floors (close-census status objects)
    # retired when the explain pass vectorized and `_close`'s emit loop
    # stopped walking the snapshot index directly — see
    # tests/test_effects.py for the fingerprint-exact baseline match
    from kube_arbitrator_tpu.analysis.report import apply_baseline, load_baseline

    _, findings = analyze_paths(
        [str(REPO / "kube_arbitrator_tpu"), str(REPO / "tests")], ALL_RULES
    )
    baseline = load_baseline(str(REPO / ".kat-baseline.json"))
    assert {f.rule for f in findings} <= {"KAT-EFF-001"}
    findings, suppressed = apply_baseline(findings, baseline)
    assert findings == [], "\n".join(f.format() for f in findings)
    assert suppressed == len(baseline)


def test_cli_exit_codes(tmp_path):
    clean = tmp_path / "clean"
    clean.mkdir()
    (clean / "ok.py").write_text("x = 1\n")
    dirty = tmp_path / "dirty"
    dirty.mkdir()
    (dirty / "bad.py").write_text("def f(:\n")

    env_cmd = [sys.executable, "-m", "kube_arbitrator_tpu.analysis"]
    r0 = subprocess.run(
        env_cmd + [str(clean)], cwd=REPO, capture_output=True, text=True
    )
    assert r0.returncode == 0, r0.stdout + r0.stderr
    assert "clean" in r0.stdout

    r1 = subprocess.run(
        env_cmd + [str(dirty)], cwd=REPO, capture_output=True, text=True
    )
    assert r1.returncode == 1, r1.stdout + r1.stderr
    assert "KAT-SYN-001" in r1.stdout
    assert "bad.py:1" in r1.stdout  # rule id + file:line in the report

    r2 = subprocess.run(
        env_cmd + ["--rules", "KAT-NOPE", str(clean)],
        cwd=REPO, capture_output=True, text=True,
    )
    assert r2.returncode == 2


def test_cli_json_and_rule_filter(tmp_path):
    import json

    bad = tmp_path / "bad.py"
    bad.write_text("def f(:\n")
    r = subprocess.run(
        [sys.executable, "-m", "kube_arbitrator_tpu.analysis", "--json", str(bad)],
        cwd=REPO, capture_output=True, text=True,
    )
    assert r.returncode == 1
    payload = json.loads(r.stdout)
    assert payload["findings"][0]["rule"] == "KAT-SYN-001"
    assert payload["findings"][0]["hint"]

    # family filter: TRC-only run ignores the syntax error? No — a file
    # that does not parse is invisible to semantic rules, so TRC alone
    # reports nothing and exits 0.  That asymmetry is why the gate always
    # runs first in the default set.
    r_trc = subprocess.run(
        [
            sys.executable, "-m", "kube_arbitrator_tpu.analysis",
            "--rules", "KAT-TRC", str(bad),
        ],
        cwd=REPO, capture_output=True, text=True,
    )
    assert r_trc.returncode == 0


def test_cli_sarif_format(tmp_path):
    import json

    bad = tmp_path / "bad.py"
    bad.write_text("def f(:\n")
    r = subprocess.run(
        [
            sys.executable, "-m", "kube_arbitrator_tpu.analysis",
            "--no-cache", "--format", "sarif", str(bad),
        ],
        cwd=REPO, capture_output=True, text=True,
    )
    assert r.returncode == 1
    sarif = json.loads(r.stdout)
    assert sarif["version"] == "2.1.0"
    run = sarif["runs"][0]
    assert run["tool"]["driver"]["name"] == "kat-lint"
    assert run["results"][0]["ruleId"] == "KAT-SYN-001"
    loc = run["results"][0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("bad.py")
    assert loc["region"]["startLine"] == 1
    assert run["results"][0]["partialFingerprints"]["katFingerprint/v1"]


def test_cli_baseline_burn_down(tmp_path):
    """The adoption workflow: record pre-existing findings, gate stays
    green on them, and a NEW violation still fails the gate."""
    src = tmp_path / "entry.py"
    src.write_text(
        "def decide(st, schedule_cycle):\n"
        "    return schedule_cycle(st, native_ops=True)\n"
    )
    baseline = tmp_path / "kat-baseline.json"
    cmd = [sys.executable, "-m", "kube_arbitrator_tpu.analysis", "--no-cache"]

    r = subprocess.run(
        cmd + ["--baseline", str(baseline), "--write-baseline", str(tmp_path)],
        cwd=REPO, capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert baseline.exists()

    r = subprocess.run(
        cmd + ["--baseline", str(baseline), str(tmp_path)],
        cwd=REPO, capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "1 baseline-suppressed" in r.stdout

    # a fresh violation of the SAME rule in another file is NOT forgiven
    (tmp_path / "entry2.py").write_text(
        "def decide2(st, schedule_cycle):\n"
        "    return schedule_cycle(st, native_ops=False)\n"
    )
    r = subprocess.run(
        cmd + ["--baseline", str(baseline), str(tmp_path)],
        cwd=REPO, capture_output=True, text=True,
    )
    assert r.returncode == 1
    assert "entry2.py" in r.stdout and "baseline-suppressed" in r.stdout


def test_fingerprint_stable_across_line_shifts():
    from kube_arbitrator_tpu.analysis.core import Finding

    a = Finding("KAT-DTY-001", "error", "m.py", 6,
                "module constant `S` (float64, bound at line 2) crosses")
    b = Finding("KAT-DTY-001", "error", "m.py", 9,
                "module constant `S` (float64, bound at line 5) crosses")
    assert a.fingerprint() == b.fingerprint()  # unrelated shift: same id
    c = Finding("KAT-DTY-001", "error", "m.py", 9,
                "module constant `T` (float64, bound at line 5) crosses")
    assert a.fingerprint() != c.fingerprint()  # different offender


LCK_FIXTURE = (
    "import threading\n"
    "\n"
    "class Service:\n"
    "    def __init__(self):\n"
    "        self._lock = threading.Lock()\n"
    "        self.count = 0\n"
    "\n"
    "    def bump(self):\n"
    "        with self._lock:\n"
    "            self.count += 1\n"
    "\n"
    "    def peek(self):\n"
    "        return self.count\n"
)


def test_fingerprint_survives_line_shift_in_real_findings(tmp_path):
    """End-to-end over real analyzer output: prepending unrelated lines
    moves the finding but keeps its baseline identity; renaming the
    offending field mints a new one."""
    f1 = run_on(tmp_path, "svc.py", LCK_FIXTURE)
    f2 = run_on(tmp_path, "svc.py", "# pad\n# pad\n# pad\n" + LCK_FIXTURE)
    assert rule_ids(f1) == rule_ids(f2) == {"KAT-LCK-001"}
    assert f2[0].line == f1[0].line + 3
    assert f1[0].fingerprint() == f2[0].fingerprint()

    f3 = run_on(tmp_path, "svc.py", LCK_FIXTURE.replace("count", "total"))
    assert rule_ids(f3) == {"KAT-LCK-001"}
    assert f3[0].fingerprint() != f1[0].fingerprint()


def test_fingerprint_redacts_embedded_line_references():
    from kube_arbitrator_tpu.analysis.core import Finding

    a = Finding("KAT-X", "error", "m.py", 1, "bad thing near line 7 here")
    b = Finding("KAT-X", "error", "m.py", 4, "bad thing near line 99 here")
    assert a.fingerprint() == b.fingerprint()  # `line <n>` redaction
    c = Finding("KAT-X", "error", "other.py", 1, "bad thing near line 7 here")
    assert a.fingerprint() != c.fingerprint()  # path still participates


def test_baseline_tolerates_hand_edited_entries(tmp_path):
    import json

    from kube_arbitrator_tpu.analysis.report import load_baseline

    p = tmp_path / "bl.json"
    p.write_text(json.dumps({
        "version": 1,
        "suppressions": {"aa": 2, "bb": {"count": 3}, "cc": {"count": "x"}},
    }))
    assert load_baseline(str(p)) == {"aa": 2, "bb": 3, "cc": 1}


DRF_BAD = (
    "def decide(st, schedule_cycle):\n"
    "    return schedule_cycle(st, native_ops=True)\n"
)


def _kat_lint(cwd, *extra):
    import os

    # cwd controls the git resolution under test; the package itself is
    # imported from the checkout
    env = dict(os.environ, PYTHONPATH=str(REPO))
    return subprocess.run(
        [sys.executable, "-m", "kube_arbitrator_tpu.analysis", "--no-cache",
         *extra],
        cwd=cwd, capture_output=True, text=True, env=env,
    )


def test_cli_changed_only_restricts_scope(tmp_path):
    def git(*a):
        subprocess.run(
            ["git", "-c", "user.email=t@t", "-c", "user.name=t", *a],
            cwd=tmp_path, check=True, capture_output=True,
        )

    git("init", "-q", "-b", "main")
    (tmp_path / "bad.py").write_text(DRF_BAD)   # committed violation
    (tmp_path / "ok.py").write_text("x = 1\n")
    git("add", ".")
    git("commit", "-q", "-m", "base")

    # nothing changed: clean exit without analyzing anything
    r = _kat_lint(tmp_path, "--changed-only", str(tmp_path))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "no changed python files" in r.stdout

    # a clean working-tree edit: only ok.py is in scope, so the committed
    # violation in bad.py does not gate the fast path
    (tmp_path / "ok.py").write_text("x = 2\n")
    r = _kat_lint(tmp_path, "--changed-only", str(tmp_path))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "changed-only: 1 file(s)" in r.stdout
    r_full = _kat_lint(tmp_path, str(tmp_path))
    assert r_full.returncode == 1  # the full gate still sees bad.py

    # an untracked new violation IS in the changed set
    (tmp_path / "new.py").write_text(DRF_BAD)
    r = _kat_lint(tmp_path, "--changed-only", str(tmp_path))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "new.py" in r.stdout and "bad.py" not in r.stdout


def test_cli_changed_only_falls_back_without_git(tmp_path):
    (tmp_path / "bad.py").write_text(DRF_BAD)
    # cwd is the non-repo tmp dir, so git resolution fails and the flag
    # degrades to the full tree instead of silently linting nothing
    r = _kat_lint(tmp_path, "--changed-only", str(tmp_path))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "git unavailable, full tree" in r.stdout
    assert "KAT-DRF-002" in r.stdout


def test_cli_json_conflicts_with_other_format(tmp_path):
    ok = tmp_path / "ok.py"
    ok.write_text("x = 1\n")
    r = subprocess.run(
        [
            sys.executable, "-m", "kube_arbitrator_tpu.analysis",
            "--no-cache", "--json", "--format", "sarif", str(ok),
        ],
        cwd=REPO, capture_output=True, text=True,
    )
    assert r.returncode == 2
    assert "conflicts" in r.stderr


def test_cache_roundtrip_and_invalidation(tmp_path):
    from kube_arbitrator_tpu.analysis.cache import AnalysisCache
    from kube_arbitrator_tpu.analysis.core import analyze_paths

    src = tmp_path / "kern.py"
    src.write_text(
        "import jax\nimport jax.numpy as jnp\n\n"
        "@jax.jit\ndef kern(x):\n"
        "    if jnp.sum(x) > 0:\n        x = x + 1\n    return x\n"
    )
    cache = AnalysisCache(str(tmp_path / "cache"))
    _, first = analyze_paths([str(src)], ALL_RULES, cache=cache, context_fp="fp")
    assert {f.rule for f in first} == {"KAT-TRC-001"}
    assert cache.hits == 0

    cache2 = AnalysisCache(str(tmp_path / "cache"))
    _, second = analyze_paths([str(src)], ALL_RULES, cache=cache2, context_fp="fp")
    assert cache2.hits == 1 and cache2.misses == 0
    assert [f.format() for f in second] == [f.format() for f in first]

    # rule-set fingerprint change invalidates
    cache3 = AnalysisCache(str(tmp_path / "cache"))
    _, third = analyze_paths([str(src)], ALL_RULES, cache=cache3, context_fp="fp2")
    assert cache3.misses == 1
    assert {f.rule for f in third} == {"KAT-TRC-001"}


def test_cache_content_key_defeats_stat_preserving_rewrite(tmp_path):
    """The v2 staleness fix: a rewrite that preserves BOTH size and mtime
    (editor atomic replace + utime) must still invalidate, because the
    key is a content hash, not the stat triple."""
    import os

    from kube_arbitrator_tpu.analysis.cache import AnalysisCache

    bad = (
        "import jax\nimport jax.numpy as jnp\n\n"
        "@jax.jit\ndef kern(x):\n"
        "    if jnp.sum(x) > 0:\n        x = x + 1\n    return x\n"
    )
    ok = bad.replace("jnp.sum(x)", "notracedv0")  # same byte length
    assert len(ok) == len(bad)
    src = tmp_path / "kern.py"
    src.write_text(bad)
    cache = AnalysisCache(str(tmp_path / "cache"))
    _, f1 = analyze_paths([str(src)], ALL_RULES, cache=cache, context_fp="fp")
    assert rule_ids(f1) == {"KAT-TRC-001"}
    cache.flush()

    st = os.stat(src)
    src.write_text(ok)
    os.utime(src, ns=(st.st_atime_ns, st.st_mtime_ns))  # stat pair identical
    cache2 = AnalysisCache(str(tmp_path / "cache"))
    _, f2 = analyze_paths([str(src)], ALL_RULES, cache=cache2, context_fp="fp")
    assert cache2.hits == 0 and cache2.misses == 1
    assert f2 == []


def test_cache_kernel_registration_invalidates_other_module(tmp_path):
    """ACTION_KERNELS context is folded into every per-file key: a new
    registration in module A legitimately changes module B's verdict."""
    from kube_arbitrator_tpu.analysis.cache import AnalysisCache

    helper = tmp_path / "helper.py"
    helper.write_text(
        "import jax.numpy as jnp\n\n"
        "def my_action(st):\n"
        "    while jnp.any(st > 0):\n        st = st - 1\n    return st\n"
    )
    reg = tmp_path / "reg.py"
    reg.write_text("X = 1\n")
    paths = [str(helper), str(reg)]

    cache = AnalysisCache(str(tmp_path / "cache"))
    _, first = analyze_paths(paths, ALL_RULES, cache=cache, context_fp="fp")
    assert first == []  # unregistered helper is not kernel context
    cache.flush()

    reg.write_text('ACTION_KERNELS = {"my": my_action}\n')
    cache2 = AnalysisCache(str(tmp_path / "cache"))
    _, second = analyze_paths(paths, ALL_RULES, cache=cache2, context_fp="fp")
    assert cache2.hits == 0  # helper.py unchanged on disk, still a miss
    assert rule_ids(second) == {"KAT-TRC-001"}


def test_cache_corrupt_and_version_mismatch_discarded(tmp_path):
    import json
    import os

    from kube_arbitrator_tpu.analysis.cache import AnalysisCache

    src = tmp_path / "ok.py"
    src.write_text("x = 1\n")
    cdir = tmp_path / "cache"

    os.makedirs(cdir)
    (cdir / "findings.json").write_text("{not json")
    cache = AnalysisCache(str(cdir))
    _, findings = analyze_paths([str(src)], ALL_RULES, cache=cache, context_fp="fp")
    assert findings == [] and cache.hits == 0 and cache.misses == 1
    cache.flush()

    # a version bump must miss wholesale, never serve old-format entries
    data = json.loads((cdir / "findings.json").read_text())
    data["version"] = 999
    (cdir / "findings.json").write_text(json.dumps(data))
    cache2 = AnalysisCache(str(cdir))
    _, findings = analyze_paths([str(src)], ALL_RULES, cache=cache2, context_fp="fp")
    assert findings == [] and cache2.hits == 0 and cache2.misses == 1


def test_ruleset_fingerprint_tracks_rule_source_edits():
    import os

    import kube_arbitrator_tpu.analysis.rules.locks as locks_mod
    from kube_arbitrator_tpu.analysis.cache import ruleset_fingerprint

    fp1 = ruleset_fingerprint(["KAT-LCK"])
    assert ruleset_fingerprint(["KAT-DTY"]) != fp1  # family selection counts
    p = locks_mod.__file__
    st = os.stat(p)
    try:
        os.utime(p, ns=(st.st_atime_ns, st.st_mtime_ns + 1))
        assert ruleset_fingerprint(["KAT-LCK"]) != fp1  # rule edit counts
    finally:
        os.utime(p, ns=(st.st_atime_ns, st.st_mtime_ns))
    assert ruleset_fingerprint(["KAT-LCK"]) == fp1


# ---------------------------------------------------------------------------
# regressions from review


def test_trc_bare_jax_numpy_import_does_not_taint_jax_namespace(tmp_path):
    # `import jax.numpy` binds `jax`; jax.device_count() etc. must not
    # count as traced-jnp evidence (only jax.numpy.<fn> does)
    findings = run_on(
        tmp_path,
        "kern.py",
        """
        import jax
        import jax.numpy

        @jax.jit
        def kern(x):
            if jax.device_count() > 1:     # host metadata: legal
                x = x + 1
            return jax.numpy.where(x > 0, x, 0)

        @jax.jit
        def kern2(x):
            if jax.numpy.sum(x) > 0:       # dotted jnp call: still flagged
                x = x + 1
            return x
        """,
    )
    assert rule_ids(findings) == {"KAT-TRC-001"}
    assert len(findings) == 1 and findings[0].line == 13


def test_rtr_nested_function_jit_call_reported_once(tmp_path):
    findings = run_on(
        tmp_path,
        "mod.py",
        """
        import jax

        def outer(f, x):
            def inner():
                return jax.jit(f)(x)
            return inner()
        """,
    )
    rtr1 = [f for f in findings if f.rule == "KAT-RTR-001"]
    assert len(rtr1) == 1
    assert "inner" in rtr1[0].message  # attributed to the innermost fn


def test_pur_global_declaration_still_flags_captured_append(tmp_path):
    findings = run_on(
        tmp_path,
        "kern.py",
        """
        import jax

        SEEN = []

        @jax.jit
        def kern(x):
            global SEEN
            SEEN.append(1)
            return x
        """,
    )
    assert rule_ids(findings) == {"KAT-PUR-003"}


def test_drf_decision_route_helper_counts_as_the_seam(tmp_path):
    findings = run_on(
        tmp_path,
        "entry.py",
        """
        from kube_arbitrator_tpu.platform import decision_route

        def decide(st, schedule_cycle, actions):
            ctx, dev, native_ops = decision_route(
                int(st.task_valid.shape[0]), actions, st.task_status
            )
            with ctx:
                return schedule_cycle(st, native_ops=native_ops)
        """,
    )
    assert findings == []
