"""Why-unschedulable diagnostics (FitError histogram parity) + events."""
import numpy as np

from kube_arbitrator_tpu.api import Taint
from kube_arbitrator_tpu.cache import SimCluster, build_snapshot
from kube_arbitrator_tpu.framework import Scheduler, Session
from kube_arbitrator_tpu.ops import schedule_cycle
from kube_arbitrator_tpu.ops.diagnostics import explain_job, unschedulable_report

GB = 1024**3


def test_explain_insufficient_resources():
    sim = SimCluster()
    sim.add_queue("q")
    sim.add_node("small1", cpu_milli=1000, memory=8 * GB)
    sim.add_node("small2", cpu_milli=1000, memory=1 * GB)
    j = sim.add_job("big", queue="q", min_available=1)
    sim.add_task(j, 4000, 4 * GB)
    snap = build_snapshot(sim.cluster)
    dec = schedule_cycle(snap.tensors)
    msg = explain_job(snap, dec, j.ordinal)
    assert msg is not None
    assert "0/2 nodes are available" in msg
    assert "Insufficient cpu" in msg
    assert "Insufficient memory" in msg  # small2 also lacks memory


def test_explain_predicate_and_unschedulable_nodes():
    sim = SimCluster()
    sim.add_queue("q")
    sim.add_node("tainted", taints=[Taint("k", "v", "NoSchedule")])
    sim.add_node("cordoned", unschedulable=True)
    j = sim.add_job("j", queue="q", min_available=1)
    sim.add_task(j, 100, 0)
    snap = build_snapshot(sim.cluster)
    dec = schedule_cycle(snap.tensors)
    msg = explain_job(snap, dec, j.ordinal)
    assert "0/2 nodes are available" in msg
    assert "selector/affinity/taints" in msg
    assert "unschedulable" in msg


def test_unschedulable_report_and_condition_message():
    sim = SimCluster()
    sim.add_queue("q")
    sim.add_node("n1", cpu_milli=1000, memory=GB)
    j = sim.add_job("gang", queue="q", min_available=3)
    for _ in range(3):
        sim.add_task(j, 1000, GB)
    res = Session(sim.cluster).run()
    report = unschedulable_report(res.snapshot, res.decisions)
    assert "gang" in report
    cond = res.job_status["gang"].conditions[0]
    assert "tasks in gang unschedulable" in cond.message
    assert "nodes are available" in cond.message


def test_scheduler_records_events():
    sim = SimCluster()
    sim.add_queue("q")
    sim.add_node("n1", cpu_milli=1000, memory=GB)
    j = sim.add_job("gang", queue="q", min_available=3)
    for _ in range(3):
        sim.add_task(j, 1000, GB)
    sched = Scheduler(sim)
    sched.run_once()
    kinds = {e.kind for e in sim.events}
    assert "Unschedulable" in kinds
    ev = next(e for e in sim.events if e.kind == "Unschedulable")
    assert ev.object_uid == "gang"
