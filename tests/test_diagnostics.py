"""Why-unschedulable diagnostics (FitError histogram parity) + events."""
import numpy as np

from kube_arbitrator_tpu.api import Taint
from kube_arbitrator_tpu.cache import SimCluster, build_snapshot
from kube_arbitrator_tpu.framework import Scheduler, Session
from kube_arbitrator_tpu.ops import schedule_cycle
from kube_arbitrator_tpu.ops.diagnostics import explain_job, unschedulable_report

GB = 1024**3


def test_explain_insufficient_resources():
    sim = SimCluster()
    sim.add_queue("q")
    sim.add_node("small1", cpu_milli=1000, memory=8 * GB)
    sim.add_node("small2", cpu_milli=1000, memory=1 * GB)
    j = sim.add_job("big", queue="q", min_available=1)
    sim.add_task(j, 4000, 4 * GB)
    snap = build_snapshot(sim.cluster)
    dec = schedule_cycle(snap.tensors)
    msg = explain_job(snap, dec, j.ordinal)
    assert msg is not None
    assert "0/2 nodes are available" in msg
    assert "Insufficient cpu" in msg
    assert "Insufficient memory" in msg  # small2 also lacks memory


def test_explain_predicate_and_unschedulable_nodes():
    sim = SimCluster()
    sim.add_queue("q")
    sim.add_node("tainted", taints=[Taint("k", "v", "NoSchedule")])
    sim.add_node("cordoned", unschedulable=True)
    j = sim.add_job("j", queue="q", min_available=1)
    sim.add_task(j, 100, 0)
    snap = build_snapshot(sim.cluster)
    dec = schedule_cycle(snap.tensors)
    msg = explain_job(snap, dec, j.ordinal)
    assert "0/2 nodes are available" in msg
    assert "selector/affinity/taints" in msg
    assert "unschedulable" in msg


def test_unschedulable_report_and_condition_message():
    sim = SimCluster()
    sim.add_queue("q")
    sim.add_node("n1", cpu_milli=1000, memory=GB)
    j = sim.add_job("gang", queue="q", min_available=3)
    for _ in range(3):
        sim.add_task(j, 1000, GB)
    res = Session(sim.cluster).run()
    report = unschedulable_report(res.snapshot, res.decisions)
    assert "gang" in report
    cond = res.job_status["gang"].conditions[0]
    assert "tasks in gang unschedulable" in cond.message
    assert "nodes are available" in cond.message


def test_scheduler_records_events():
    sim = SimCluster()
    sim.add_queue("q")
    sim.add_node("n1", cpu_milli=1000, memory=GB)
    j = sim.add_job("gang", queue="q", min_available=3)
    for _ in range(3):
        sim.add_task(j, 1000, GB)
    sched = Scheduler(sim)
    sched.run_once()
    kinds = {e.kind for e in sim.events}
    assert "Unschedulable" in kinds
    ev = next(e for e in sim.events if e.kind == "Unschedulable")
    assert ev.object_uid == "gang"


def test_every_pod_of_blocked_gang_gets_condition():
    """VERDICT round-2 #6: the per-pod condition channel must cover EVERY
    unplaced pending pod of a blocked gang (cache.go:456-474 stamps
    PodScheduled=False per task), not just the first task of the first 100
    jobs."""
    from kube_arbitrator_tpu.cache import SimCluster
    from kube_arbitrator_tpu.framework import Scheduler

    sim = SimCluster()
    sim.add_queue("q")
    sim.add_node("n1", cpu_milli=2000, memory=4 * GB)
    # gang of 8 x 1cpu on a 2cpu node: can never reach minMember=8
    j = sim.add_job("gang", queue="q", min_available=8)
    for i in range(8):
        sim.add_task(j, 1000, GB // 4, name=f"g-{i}")
    sched = Scheduler(sim)
    result = sched.run_once()

    assert set(result.task_conditions) == {f"g-{i}" for i in range(8)}
    for msg in result.task_conditions.values():
        assert "nodes are available" in msg and "Insufficient cpu" in msg
    # the backend recorded them (fake StatusUpdater surface)
    assert set(sim.pod_conditions) == {f"g-{i}" for i in range(8)}


def test_pod_conditions_reach_fake_apiserver():
    """Live plane: the conditions are PATCHed onto the pod objects."""
    from kube_arbitrator_tpu.cache import FakeApiServer, LiveCache
    from kube_arbitrator_tpu.framework import Scheduler
    from tests.test_live_cache import make_node, make_pod, make_podgroup

    api = FakeApiServer()
    api.create("nodes", make_node("n0", cpu="1"))
    api.create("queues", {"metadata": {"name": "default"}, "spec": {"weight": 1}})
    api.create("podgroups", make_podgroup("pg", min_member=4))
    for i in range(4):
        api.create("pods", make_pod(f"p{i}", group="pg", cpu="1"))
    live = LiveCache(api)
    Scheduler(live).run_once()
    for i in range(4):
        pod = api.get("pods", "default", f"p{i}")
        conds = pod["status"].get("conditions", [])
        assert any(
            c["type"] == "PodScheduled" and c["status"] == "False" and c["message"]
            for c in conds
        ), f"p{i} missing PodScheduled condition"


def _blocked_gang_world():
    """A gang that can never reach minMember (8 x 1cpu vs a 2cpu node) —
    the explain_pending_tasks fixture shared by the path-coverage tests."""
    sim = SimCluster()
    sim.add_queue("q")
    sim.add_node("n1", cpu_milli=2000, memory=4 * GB)
    j = sim.add_job("gang", queue="q", min_available=8)
    for i in range(8):
        sim.add_task(j, 1000, GB // 4, name=f"g-{i}")
    return sim


def test_explain_pending_tasks_under_arena_path():
    """The per-pod condition channel must work identically when the
    snapshot comes from the incremental arena (delta-maintained pack),
    not just the full-rebuild path — and the reason histogram lands in
    pending_reason_total{reason}."""
    from kube_arbitrator_tpu.utils.metrics import metrics

    sim = _blocked_gang_world()
    sched = Scheduler(sim, arena=True)
    before = metrics().counter_value(
        "pending_reason_total", labels={"reason": "Insufficient cpu"}
    )
    result = sched.run_once()
    assert set(result.task_conditions) == {f"g-{i}" for i in range(8)}
    for msg in result.task_conditions.values():
        assert "nodes are available" in msg and "Insufficient cpu" in msg
    assert set(sim.pod_conditions) == {f"g-{i}" for i in range(8)}
    after = metrics().counter_value(
        "pending_reason_total", labels={"reason": "Insufficient cpu"}
    )
    assert after - before == 8


def test_explain_pending_tasks_under_pipelined_path():
    """run_pipelined derives the conditions on its decide worker and the
    write-back must still stamp every blocked pod + count the reasons —
    the path test_diagnostics previously never exercised."""
    from kube_arbitrator_tpu.utils.metrics import metrics

    sim = _blocked_gang_world()
    sched = Scheduler(sim, arena=True)
    before = metrics().counter_value(
        "pending_reason_total", labels={"reason": "Insufficient cpu"}
    )
    cycles = sched.run_pipelined(max_cycles=2, until_idle=False)
    assert cycles == 2
    assert set(sim.pod_conditions) == {f"g-{i}" for i in range(8)}
    for msg in sim.pod_conditions.values():
        assert "nodes are available" in msg and "Insufficient cpu" in msg
    after = metrics().counter_value(
        "pending_reason_total", labels={"reason": "Insufficient cpu"}
    )
    assert after - before == 8 * cycles


def test_pending_reason_counts_attribute_dominant_and_gang_reasons():
    """explain_pending_tasks_with_reasons: node-blocked pods carry their
    dominant FitError reason; pods whose group HAS fitting nodes but sit
    behind an unready gang are attributed 'gang not ready'."""
    from kube_arbitrator_tpu.ops.diagnostics import (
        explain_pending_tasks_with_reasons,
    )

    sim = SimCluster()
    sim.add_queue("q")
    sim.add_node("n1", cpu_milli=2000, memory=4 * GB)
    # mixed-size gang: the small group's pods fit (and get session-
    # Allocated) but the huge group can never fit, so minMember=4 blocks
    # the whole gang — at close the small pods still see fitting
    # capacity (gang-blocked), the huge ones see Insufficient cpu
    j = sim.add_job("gang", queue="q", min_available=4)
    for i in range(2):
        sim.add_task(j, 500, GB // 4, name=f"small-{i}")
    for i in range(2):
        sim.add_task(j, 4000, GB // 4, name=f"huge-{i}")
    snap = build_snapshot(sim.cluster)
    dec = schedule_cycle(snap.tensors)
    conditions, reasons = explain_pending_tasks_with_reasons(snap, dec)
    assert set(conditions) == {"small-0", "small-1", "huge-0", "huge-1"}
    assert reasons == {"Insufficient cpu": 2, "gang not ready": 2}, reasons
