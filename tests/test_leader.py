"""Leader election: lease acquisition, renewal, expiry takeover, fatal loss.

Reference semantics: cmd/kube-batch/app/server.go:102-125 — only the leader
schedules; losing the lease is fatal.
"""
import pytest

from kube_arbitrator_tpu.cache import SimCluster
from kube_arbitrator_tpu.framework import LeaderElector, LeaderLost, Scheduler

GB = 1024**3


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _elector(path, ident, clock, **kw):
    return LeaderElector(str(path), identity=ident, now_fn=clock, **kw)


def test_first_contender_wins_second_waits(tmp_path):
    clock = FakeClock()
    lock = tmp_path / "kb.lock"
    a = _elector(lock, "a", clock)
    b = _elector(lock, "b", clock)
    assert a.try_acquire()
    assert not b.try_acquire()
    assert a.is_leader and not b.is_leader


def test_renewal_keeps_lease_alive(tmp_path):
    clock = FakeClock()
    lock = tmp_path / "kb.lock"
    a = _elector(lock, "a", clock, lease_duration_s=15, renew_deadline_s=10)
    b = _elector(lock, "b", clock)
    assert a.try_acquire()
    for _ in range(10):
        clock.t += 5.0
        assert a.renew()
        assert not b.try_acquire()


def test_stale_lease_taken_over(tmp_path):
    clock = FakeClock()
    lock = tmp_path / "kb.lock"
    a = _elector(lock, "a", clock, lease_duration_s=15)
    b = _elector(lock, "b", clock)
    assert a.try_acquire()
    clock.t += 16.0  # lease expired, never renewed
    # observer-local lease timing (client-go observedTime): b never trusts
    # the holder's embedded timestamp against its own clock — it must see
    # the record UNCHANGED for a full lease_duration on its own clock
    # before stealing (cross-host clock skew protection)
    assert not b.try_acquire()
    clock.t += 16.0  # observed unchanged past a full lease duration
    assert b.try_acquire()
    # usurped: a's renewal must now fail
    assert not a.renew()
    assert not a.is_leader


def test_renew_deadline_is_fatal_even_without_usurper(tmp_path):
    clock = FakeClock()
    lock = tmp_path / "kb.lock"
    a = _elector(lock, "a", clock, lease_duration_s=30, renew_deadline_s=10)
    assert a.try_acquire()
    clock.t += 11.0  # missed the renew deadline
    assert not a.renew()


def test_release_hands_over_immediately(tmp_path):
    clock = FakeClock()
    lock = tmp_path / "kb.lock"
    a = _elector(lock, "a", clock)
    b = _elector(lock, "b", clock)
    assert a.try_acquire()
    a.release()
    assert b.try_acquire()


def test_scheduler_gated_on_leadership_and_loss_is_fatal(tmp_path):
    clock = FakeClock()
    lock = tmp_path / "kb.lock"
    leader = _elector(lock, "leader", clock)
    standby = _elector(lock, "standby", clock)
    assert leader.try_acquire()

    sim = SimCluster()
    sim.add_queue("default")
    sim.add_node("n1", cpu_milli=4000, memory=8 * GB)
    job = sim.add_job("j1")
    sim.add_task(job, cpu_milli=500, memory=GB)

    # standby loses acquisition within its timeout → never schedules
    assert not standby.acquire_blocking(timeout_s=0.0)

    sched = Scheduler(sim, elector=leader)
    sched.run(max_cycles=1)
    assert len(sim.binder.binds) == 1

    # lease usurped between cycles → next run dies
    clock.t += 100.0
    assert standby.try_acquire()
    with pytest.raises(LeaderLost):
        sched.run(max_cycles=1)


def test_timing_ordering_validated(tmp_path):
    """client-go's NewLeaderElector ordering: lease_duration >
    renew_deadline > retry_period > 0 — a misconfigured pair (e.g.
    renew_deadline >= lease_duration) would silently permit two
    concurrent leaders via the renew-blip grace, so both electors must
    refuse to construct."""
    import pytest

    from kube_arbitrator_tpu.framework.leader import LeaderElector

    path = str(tmp_path / "lock")
    with pytest.raises(ValueError, match="lease_duration"):
        LeaderElector(path, lease_duration_s=10.0, renew_deadline_s=10.0)
    with pytest.raises(ValueError, match="renew_deadline"):
        LeaderElector(path, lease_duration_s=15.0, renew_deadline_s=5.0,
                      retry_period_s=5.0)
    with pytest.raises(ValueError, match="retry_period"):
        LeaderElector(path, lease_duration_s=15.0, renew_deadline_s=10.0,
                      retry_period_s=0.0)


def _one_task_sim():
    sim = SimCluster()
    sim.add_queue("default")
    sim.add_node("n1", cpu_milli=4000, memory=8 * GB)
    job = sim.add_job("j1")
    sim.add_task(job, cpu_milli=500, memory=GB)
    return sim


def _slow_decider(clock, dt, mid_decision=None):
    """A decider whose decision phase 'takes' dt seconds on the fake
    clock (the wedged-accelerator shape), optionally running a callback
    mid-decision (e.g. a standby stealing the lease)."""
    from kube_arbitrator_tpu.framework.decider import LocalDecider

    class WedgedDecider(LocalDecider):
        def decide(self, st, config, pack_meta=None):
            out = super().decide(st, config)
            clock.t += dt
            if mid_decision is not None:
                mid_decision()
            return out

    return WedgedDecider()


def test_slow_cycle_revalidates_against_storage_and_actuates(tmp_path):
    """ADVICE r5 fence false-positive: a cycle slower than the renew
    deadline looks stale to the clock-only lease_fresh(), but with NO
    usurper the lease record still names this leader — the fence's
    elector.revalidate() confirms against storage, renews, and the cycle
    actuates instead of killing a healthy process."""
    clock = FakeClock()
    leader = _elector(tmp_path / "kb.lock", "leader", clock,
                      lease_duration_s=15, renew_deadline_s=10)
    assert leader.try_acquire()
    sim = _one_task_sim()
    # 12 s decision: past renew_deadline (10), inside lease_duration (15)
    sched = Scheduler(sim, elector=leader, decider=_slow_decider(clock, 12.0))
    sched.run(max_cycles=1)
    assert len(sim.binder.binds) == 1, "slow-but-healthy cycle must actuate"
    assert leader.is_leader  # re-validation restored leadership + renew_ts


def test_usurped_lease_after_decision_discards_cycle(tmp_path):
    """The fence's real target: a decision phase so long a standby
    legally took the lease.  revalidate() sees the other holder and the
    stale binds are discarded with LeaderLost before apply_binds."""
    clock = FakeClock()
    lock = tmp_path / "kb.lock"
    leader = _elector(lock, "leader", clock)
    standby = _elector(lock, "standby", clock)
    assert leader.try_acquire()
    sim = _one_task_sim()

    def standby_takes_over():
        # observer-local lease timing: the standby must watch the record
        # unchanged for a full lease_duration before it may steal
        assert not standby.try_acquire()
        clock.t += 20.0
        assert standby.try_acquire()

    sched = Scheduler(
        sim, elector=leader, decider=_slow_decider(clock, 20.0, standby_takes_over)
    )
    with pytest.raises(LeaderLost, match="not actuated"):
        sched.run(max_cycles=1)
    assert sim.binder.binds == {}, "stale cycle must not actuate"
    assert not leader.is_leader


def test_revalidate_fails_on_transient_storage_error(tmp_path, monkeypatch):
    """Storage that cannot CONFIRM leadership must not let a stale cycle
    actuate: revalidate() treats an unreadable lock as lost."""
    from kube_arbitrator_tpu.framework.leader import TransientLockError

    clock = FakeClock()
    leader = _elector(tmp_path / "kb.lock", "leader", clock)
    assert leader.try_acquire()
    clock.t += 12.0  # past renew deadline

    def boom():
        raise TransientLockError("storage unreachable")

    monkeypatch.setattr(leader, "_fetch", boom)
    assert not leader.revalidate()
    assert not leader.is_leader
