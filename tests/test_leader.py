"""Leader election: lease acquisition, renewal, expiry takeover, fatal loss.

Reference semantics: cmd/kube-batch/app/server.go:102-125 — only the leader
schedules; losing the lease is fatal.
"""
import pytest

from kube_arbitrator_tpu.cache import SimCluster
from kube_arbitrator_tpu.framework import LeaderElector, LeaderLost, Scheduler

GB = 1024**3


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _elector(path, ident, clock, **kw):
    return LeaderElector(str(path), identity=ident, now_fn=clock, **kw)


def test_first_contender_wins_second_waits(tmp_path):
    clock = FakeClock()
    lock = tmp_path / "kb.lock"
    a = _elector(lock, "a", clock)
    b = _elector(lock, "b", clock)
    assert a.try_acquire()
    assert not b.try_acquire()
    assert a.is_leader and not b.is_leader


def test_renewal_keeps_lease_alive(tmp_path):
    clock = FakeClock()
    lock = tmp_path / "kb.lock"
    a = _elector(lock, "a", clock, lease_duration_s=15, renew_deadline_s=10)
    b = _elector(lock, "b", clock)
    assert a.try_acquire()
    for _ in range(10):
        clock.t += 5.0
        assert a.renew()
        assert not b.try_acquire()


def test_stale_lease_taken_over(tmp_path):
    clock = FakeClock()
    lock = tmp_path / "kb.lock"
    a = _elector(lock, "a", clock, lease_duration_s=15)
    b = _elector(lock, "b", clock)
    assert a.try_acquire()
    clock.t += 16.0  # lease expired, never renewed
    # observer-local lease timing (client-go observedTime): b never trusts
    # the holder's embedded timestamp against its own clock — it must see
    # the record UNCHANGED for a full lease_duration on its own clock
    # before stealing (cross-host clock skew protection)
    assert not b.try_acquire()
    clock.t += 16.0  # observed unchanged past a full lease duration
    assert b.try_acquire()
    # usurped: a's renewal must now fail
    assert not a.renew()
    assert not a.is_leader


def test_renew_deadline_is_fatal_even_without_usurper(tmp_path):
    clock = FakeClock()
    lock = tmp_path / "kb.lock"
    a = _elector(lock, "a", clock, lease_duration_s=30, renew_deadline_s=10)
    assert a.try_acquire()
    clock.t += 11.0  # missed the renew deadline
    assert not a.renew()


def test_release_hands_over_immediately(tmp_path):
    clock = FakeClock()
    lock = tmp_path / "kb.lock"
    a = _elector(lock, "a", clock)
    b = _elector(lock, "b", clock)
    assert a.try_acquire()
    a.release()
    assert b.try_acquire()


def test_scheduler_gated_on_leadership_and_loss_is_fatal(tmp_path):
    clock = FakeClock()
    lock = tmp_path / "kb.lock"
    leader = _elector(lock, "leader", clock)
    standby = _elector(lock, "standby", clock)
    assert leader.try_acquire()

    sim = SimCluster()
    sim.add_queue("default")
    sim.add_node("n1", cpu_milli=4000, memory=8 * GB)
    job = sim.add_job("j1")
    sim.add_task(job, cpu_milli=500, memory=GB)

    # standby loses acquisition within its timeout → never schedules
    assert not standby.acquire_blocking(timeout_s=0.0)

    sched = Scheduler(sim, elector=leader)
    sched.run(max_cycles=1)
    assert len(sim.binder.binds) == 1

    # lease usurped between cycles → next run dies
    clock.t += 100.0
    assert standby.try_acquire()
    with pytest.raises(LeaderLost):
        sched.run(max_cycles=1)


def test_timing_ordering_validated(tmp_path):
    """client-go's NewLeaderElector ordering: lease_duration >
    renew_deadline > retry_period > 0 — a misconfigured pair (e.g.
    renew_deadline >= lease_duration) would silently permit two
    concurrent leaders via the renew-blip grace, so both electors must
    refuse to construct."""
    import pytest

    from kube_arbitrator_tpu.framework.leader import LeaderElector

    path = str(tmp_path / "lock")
    with pytest.raises(ValueError, match="lease_duration"):
        LeaderElector(path, lease_duration_s=10.0, renew_deadline_s=10.0)
    with pytest.raises(ValueError, match="renew_deadline"):
        LeaderElector(path, lease_duration_s=15.0, renew_deadline_s=5.0,
                      retry_period_s=5.0)
    with pytest.raises(ValueError, match="retry_period"):
        LeaderElector(path, lease_duration_s=15.0, renew_deadline_s=10.0,
                      retry_period_s=0.0)


def test_stale_lease_after_decision_discards_cycle(tmp_path):
    """A decision phase that outlasts the renew deadline (wedged
    accelerator tunnel) must NOT actuate its stale binds: the actuation
    fence in Scheduler._run_once_inner discards the cycle with LeaderLost
    before apply_binds, so a standby that took the lease mid-decision
    never co-exists with a stale actuator."""
    clock = FakeClock()
    lock = tmp_path / "kb.lock"
    leader = _elector(lock, "leader", clock)
    assert leader.try_acquire()

    sim = SimCluster()
    sim.add_queue("default")
    sim.add_node("n1", cpu_milli=4000, memory=8 * GB)
    job = sim.add_job("j1")
    sim.add_task(job, cpu_milli=500, memory=GB)

    # simulate the decision program hanging past the renew deadline:
    # advance the fake clock inside the decide path
    from kube_arbitrator_tpu.framework.decider import LocalDecider

    class WedgedDecider(LocalDecider):
        def decide(self, st, config):
            out = super().decide(st, config)
            clock.t += 1000.0  # decision "took" far past renew_deadline_s
            return out

    sched = Scheduler(sim, elector=leader, decider=WedgedDecider())
    with pytest.raises(LeaderLost, match="not actuated"):
        sched.run(max_cycles=1)
    assert sim.binder.binds == {}, "stale cycle must not actuate"

    # control: a fresh lease actuates normally
    clock2 = FakeClock()
    lock2 = tmp_path / "kb2.lock"
    leader2 = _elector(lock2, "leader2", clock2)
    assert leader2.try_acquire()
    sim2 = SimCluster()
    sim2.add_queue("default")
    sim2.add_node("n1", cpu_milli=4000, memory=8 * GB)
    j2 = sim2.add_job("j1")
    sim2.add_task(j2, cpu_milli=500, memory=GB)
    Scheduler(sim2, elector=leader2).run(max_cycles=1)
    assert len(sim2.binder.binds) == 1
