"""Volume binding semantics: attach limits + PV zone pinning.

Reference: the k8s volumebinder wired at ``cache.go:230-238`` and called
at every allocation/dispatch (``session.go:243-259`` AllocateVolumes,
``:295-316`` BindVolumes).  TPU-native shape: attach COUNTS are the 4th
resource axis (every fit/claim kernel enforces the limit for free); PV
ZONE pinning rides the predicate class table; the FakeVolumeBinder
re-checks at actuation and failures roll back gang-atomically through the
errTasks resync FIFO.
"""
import numpy as np

from kube_arbitrator_tpu.api import TaskStatus
from kube_arbitrator_tpu.api import resource as res
from kube_arbitrator_tpu.cache import SimCluster, build_snapshot
from kube_arbitrator_tpu.cache.decode import decode_decisions
from kube_arbitrator_tpu.framework import Scheduler
from kube_arbitrator_tpu.ops import schedule_cycle

GB = 1024**3
ZONE = "topology.kubernetes.io/zone"


def run(sim):
    snap = build_snapshot(sim.cluster)
    dec = schedule_cycle(snap.tensors)
    binds, evicts = decode_decisions(snap, dec)
    return {b.task_uid: b.node_name for b in binds}


def test_attach_limit_rejects_cpu_feasible_task():
    """VERDICT #7 'done': a task that fits CPU-wise but fails volume-wise
    is rejected at scheduling time."""
    sim = SimCluster()
    sim.add_queue("q")
    sim.add_node("n1", cpu_milli=8000, memory=16 * GB, attach_limit=2)
    j = sim.add_job("j", queue="q")
    sim.add_task(j, 100, 0, name="v1", volumes=1)  # scheduled first (uid order)
    sim.add_task(j, 100, 0, name="v2", volumes=2)  # cpu fits; attach does not
    binds = run(sim)
    assert binds == {"v1": "n1"}


def test_attach_limit_spreads_across_nodes():
    sim = SimCluster()
    sim.add_queue("q")
    sim.add_node("n1", cpu_milli=8000, attach_limit=1)
    sim.add_node("n2", cpu_milli=8000, attach_limit=1)
    j = sim.add_job("j", queue="q")
    for i in range(2):
        sim.add_task(j, 100, 0, name=f"t{i}", volumes=1)
    binds = run(sim)
    assert sorted(binds.values()) == ["n1", "n2"]


def test_volume_zone_pins_placement():
    """A task whose PV lives in zone-b only fits zone-b nodes even when a
    zone-a node is emptier (the VolumeZone predicate)."""
    sim = SimCluster()
    sim.add_queue("q")
    sim.add_node("a1", cpu_milli=8000, labels={ZONE: "zone-a"})
    sim.add_node("b1", cpu_milli=2000, labels={ZONE: "zone-b"})
    j = sim.add_job("j", queue="q")
    sim.add_task(j, 1000, 0, name="pinned", volumes=1, volume_zone="zone-b")
    sim.add_task(j, 1000, 0, name="free")
    binds = run(sim)
    assert binds["pinned"] == "b1"
    assert binds["free"] == "a1"  # first-fit node order


def test_volume_zone_unsatisfiable_blocks_task():
    sim = SimCluster()
    sim.add_queue("q")
    sim.add_node("a1", cpu_milli=8000, labels={ZONE: "zone-a"})
    j = sim.add_job("j", queue="q")
    sim.add_task(j, 1000, 0, name="pinned", volume_zone="zone-z")
    assert run(sim) == {}


def test_volume_failure_rolls_back_gang_batch():
    """AllocateVolumes failure drops the whole job's bind batch (the
    gang-atomic form of session.go:243-259 failing the task) and routes
    the tasks through the errTasks resync FIFO; the next cycle retries."""
    sim = SimCluster()
    sim.add_queue("q")
    sim.add_node("n1", cpu_milli=8000, memory=16 * GB)
    j = sim.add_job("gang", queue="q", min_available=2)
    sim.add_task(j, 1000, 0, name="g0", volumes=1)
    sim.add_task(j, 1000, 0, name="g1", volumes=1)
    sim.volume_binder.fail_allocate_uids = {"g1"}

    sched = Scheduler(sim)
    sched.run_once()
    # nothing committed: both tasks diverted to resync, still pending
    assert sim.binder.binds == {}
    assert any(e.kind == "FailedScheduling" for e in sim.events)
    for t in sim.cluster.jobs["gang"].tasks.values():
        assert t.status == TaskStatus.PENDING

    # failure clears -> next cycle binds the whole gang
    sim.volume_binder.fail_allocate_uids = set()
    sched.run_once()
    assert set(sim.binder.binds) == {"g0", "g1"}


def test_oracle_agrees_on_attach_limits():
    from kube_arbitrator_tpu.oracle import SequentialScheduler

    sim = SimCluster()
    sim.add_queue("q")
    sim.add_node("n1", cpu_milli=8000, attach_limit=3)
    sim.add_node("n2", cpu_milli=8000, attach_limit=1)
    j = sim.add_job("j", queue="q")
    for i in range(5):
        sim.add_task(j, 100, 0, name=f"t{i}", volumes=1)
    binds = run(sim)
    oracle = SequentialScheduler(sim.cluster).run_cycle()
    assert binds == oracle.binds
    assert len(binds) == 4  # 3 + 1 attach slots
